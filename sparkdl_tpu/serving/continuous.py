"""Continuous batching for GPT decode: rows join and leave mid-stream.

The lockstep ``generate`` path (models/gpt.py) starts a batch together
and ends it together, so one long row holds every slot hostage and new
arrivals wait for the whole batch to finish — fatal for online serving.
This engine keeps ONE persistent decode batch of ``n_slots`` rows over a
per-slot KV cache (``init_cache(per_slot=True)``: ``idx`` per row):

- a finished row frees its slot immediately;
- a newly admitted prompt is prefilled ALONE (batch-1, bucketed prompt
  length, the jit-cached left-padded ragged path) and its K/V row is
  scattered into the free slot — the in-flight neighbors never notice;
- every engine tick advances all live rows one token in a single jitted
  step whose per-row causal mask lets each row decode at its own depth.

Token identity: greedy tokens of every request are IDENTICAL to its
unbatched ``generate`` decode (tests/serving/test_continuous_gpt.py) —
batching is a scheduling decision, never a quality decision.

Decode is greedy (temperature 0), the deterministic serving default;
sampled decode stays on the lockstep ``DeepTextGenerator`` path.

KV layouts (``kv_layout=``, ROADMAP item 4):

- ``"paged"`` (default) — block-paged KV pool
  (:mod:`~sparkdl_tpu.serving.kv_blocks`): each slot maps its columns
  onto refcounted ``block_size``-token blocks through a block table,
  the jitted decode step gathers a virtual dense cache from the table
  and scatters the written column back, so persistent KV memory is
  bounded by allocated tokens, not ``n_slots x max_len``. Admission
  against an exhausted pool DEFERS (re-queues in order) instead of
  erroring. Prompts are prefilled right-aligned in bounded CHUNKS
  (``prefill_chunk`` tokens per engine tick, interleaved with decode
  ticks — a long prompt no longer freezes in-flight decode latency),
  and a radix prefix cache
  (:mod:`~sparkdl_tpu.serving.prefix_cache`) lets a request reuse the
  cached K/V of its longest shared prompt prefix and prefill only the
  suffix (partial tail blocks shared copy-on-write). Greedy tokens
  stay oracle-identical on every path (tests/serving/test_kv_paged.py).
- ``"dense"`` — the original one-dense-buffer-per-slot layout, kept as
  the parity oracle and fallback.

Speculative multi-token decoding (``spec_k=``, ROADMAP item 3): a
draft source (:mod:`~sparkdl_tpu.serving.spec_decode` — radix-trie
continuations + n-gram self-lookup by default, any ``propose()``
object, e.g. a small draft model, via ``draft_source=``) proposes up
to ``k-1`` tokens per live slot, and ONE verify dispatch scores the
whole span (the L=k per-slot step in models/gpt.py): every accepted
draft token is a decode dispatch never issued. Greedy acceptance is
exact, so accepted tokens are bitwise-identical to one-token-at-a-time
decode at every draft length — the engine's oracle contract extends
unchanged (tests/serving/test_spec_decode.py). The verify width is
re-bounded every tick by the same budget/deadline caps as
``chain_tokens`` plus the measured acceptance rate
(:class:`~sparkdl_tpu.runtime.dispatch.SpecPolicy`). The
``spec.verify`` fault site fires BEFORE the verify is dispatched (the
injectable stand-in for a verify that cannot run): the tick falls back
to plain decode — zero lost requests. An error raised by the dispatch
itself is NOT caught: the pool buffer is donated, so there is no valid
state to fall back to — it propagates like any decode-dispatch error
(the engine loop fails every pending Future loudly rather than serving
from a consumed cache).

Quantized KV blocks (``kv_dtype=``): the paged pool can store
``"bf16"`` or ``"int8"`` (one fp32 scale per written column) instead
of the compute dtype — quantize-on-scatter / dequantize-on-gather are
fused into the existing paged gather/scatter programs, so pool
capacity (and deferred-admission pressure) improves 2-4x
(:func:`~sparkdl_tpu.serving.kv_blocks.kv_capacity_ratio`) while
compute still runs at the model dtype; bench_serving's dense-vs-paged
parity harness measures the quality trade.

Sequence-parallel prefill (``sp=``, ROADMAP item 2): with ``sp=N``
the chunked prefill becomes SPATIAL — each chunk dispatches across N
chips (queries sharded on the ``sp`` mesh axis, K/V all-gathered for
the causal attention) and the accumulating prompt K/V lives in a
sequence-sharded staging pool
(:class:`~sparkdl_tpu.serving.kv_blocks.SeqShardedBlockPool`), so a
long context never has to fit one chip during prefill. ONE gather at
the prefill→decode handoff (``sp.gather`` fault site) installs the
staged K/V into the decode pool; the per-token loop — plain, chained,
speculative — is the untouched single-device paged path, which is why
greedy tokens stay bitwise across sp∈{1,2} on every decode mode. An
injected collective fault (``sp.permute``/``sp.gather``) re-queues the
victim request instead of failing it (:class:`SpCollectiveError` in
the flight ring). README "Long-context serving" has the sizing
arithmetic; PERF.md the measured trade (sp=2 prefill 2.26x at 3072
prompt tokens on the CPU harness — and a measured LOSS below ~1k
tokens, where the per-chunk fixed costs beat the query split).
"""

from __future__ import annotations

import dataclasses
import functools
import threading
import time
from concurrent.futures import Future
from typing import Any, Optional

import numpy as np

from sparkdl_tpu.observability import flight as flight_mod
from sparkdl_tpu.observability import slo as slo_mod
from sparkdl_tpu.observability import tracing
from sparkdl_tpu.observability.registry import registry
from sparkdl_tpu.observability.tracing import span
from sparkdl_tpu.reliability.faults import fault_point
from sparkdl_tpu.runtime.completion import start_fetch
from sparkdl_tpu.runtime.dispatch import (
    ChainPolicy,
    SpecPolicy,
    record_dispatch,
)
from sparkdl_tpu.serving import tenancy
from sparkdl_tpu.serving.metrics import ServingMetrics
from sparkdl_tpu.serving.queue import (
    DeadlineExceededError,
    EngineClosedError,
    Request,
    RequestQueue,
    record_request_failure,
)


_M_PREFILL_CHUNKS = registry().counter(
    "sparkdl_prefill_chunks_total",
    "bounded prefill chunks dispatched by continuous GPT engines")

_M_SP_RING_STEPS = registry().counter(
    "sparkdl_sp_ring_steps_total",
    "collective hops dispatched by sequence-parallel prefill chunks "
    "(sp - 1 per sharded chunk dispatch)")
_M_SP_PERMUTE_BYTES = registry().counter(
    "sparkdl_sp_permute_bytes_total",
    "estimated K/V bytes moved between sp chips by prefill collectives "
    "(2 x layers x chunk_width x hidden x itemsize x (sp-1) per "
    "dispatch)")


class SpCollectiveError(RuntimeError):
    """A sequence-parallel collective (ring permute hop or the
    prefill→decode handoff gather) failed. The engine never surfaces
    this to a caller: the victim request's prefill is torn down, its
    blocks released, and the request RE-QUEUED at the head — an
    already-admitted request is never lost to a collective fault (the
    ``sp.permute`` / ``sp.gather`` chaos contract)."""

_M_SPEC_PROPOSED = registry().counter(
    "sparkdl_spec_proposed_total",
    "draft tokens proposed to speculative verify dispatches")
_M_SPEC_ACCEPTED = registry().counter(
    "sparkdl_spec_accepted_total",
    "proposed draft tokens accepted by greedy verify (each one a "
    "decode dispatch never issued)")
_M_SPEC_RATE = registry().gauge(
    "sparkdl_spec_acceptance_rate",
    "cumulative accepted/proposed draft share across this process's "
    "speculative engines")
_M_SPEC_FALLBACKS = registry().counter(
    "sparkdl_spec_fallbacks_total",
    "speculative verify dispatches abandoned to plain decode "
    "(spec.verify fault site)")

#: Process-wide propose/accept totals behind the acceptance-rate gauge.
#: Several engines contribute from their own loop threads — their
#: engine locks are DIFFERENT locks, so this shared state needs its own.
_SPEC_TOTALS = {"proposed": 0, "accepted": 0}
_SPEC_TOTALS_LOCK = threading.Lock()

#: Consecutive pool-exhaustion deferrals before the flight recorder
#: writes a postmortem (one defer is normal backpressure; a streak is
#: the incident an operator will ask about).
_EXHAUST_DUMP_STREAK = 3

#: Seconds between brownout-controller evaluations fed by the engine
#: tick (ISSUE 20): the ladder's hysteresis counts these evaluations,
#: so the stride — not the tick rate — sets its reaction time.
_OVERLOAD_STRIDE_S = 0.25


@dataclasses.dataclass
class GenRequest:
    """One generation request: prompt token ids + token budget."""

    prompt: np.ndarray
    max_new_tokens: int


@dataclasses.dataclass
class _InFlight:
    """Host-side state of one occupied slot (the left-pad count lives in
    the engine's ``_start`` array the decode step consumes; ``blocks``
    are the paged layout's refcounted KV blocks, released on retire)."""

    req: Request
    produced: list[int]
    max_new: int
    blocks: "list[int] | None" = None
    #: prompt ids (paged layout): the draft proposer's context is
    #: prompt + produced — ids only, never device state
    prompt: "np.ndarray | None" = None


@dataclasses.dataclass
class _Prefill:
    """One slot mid-chunked-prefill (paged layout): the prompt's K/V are
    accumulating in a private batch-1 dense cache (``ck``/``cv``),
    ``prefill_chunk`` tokens per engine tick, until installation into
    the slot's pool blocks. ``pos`` counts prompt tokens already in the
    cache, including the ``hit`` tokens gathered from the prefix cache
    (whose prefill was skipped)."""

    req: Request
    prompt: np.ndarray
    max_new: int
    pos: int
    hit: int
    shared: "list[int]"
    owned: "list[int]"
    gather_ids: np.ndarray  # block ids backing the cached prefix
    install_ids: np.ndarray  # owned-block targets for the final chunk
    #: COW source (a shared partial tail block): holds an extra pool
    #: reference until the first chunk's gather has been dispatched
    cow_block: "int | None" = None
    ck: Any = None  # None until the first (gather-fused) chunk ran
    cv: Any = None
    chunks: int = 0
    #: sequence-parallel staging blocks (sp > 1): the prompt's K/V
    #: accumulate in these SeqShardedBlockPool blocks — sharded across
    #: the sp chips — instead of the private dense cache, until the
    #: prefill→decode handoff gathers them once
    sp_blocks: "list[int] | None" = None

    def all_blocks(self) -> "list[int]":
        """Every pool reference this prefill holds (release on abort)."""
        return (self.shared + self.owned
                + ([self.cow_block] if self.cow_block is not None
                   else []))


class ContinuousGPTEngine:
    """Async continuous-batching GPT server.

    ``submit(prompt_ids, max_new_tokens)`` returns a Future of the
    generated ids (prompt not included). Admission control is two-layer:
    queue depth (QueueFullError) and cache capacity. Under
    ``kv_layout="dense"`` a request whose BUCKETED prompt + budget
    cannot fit ``max_len`` columns is rejected at submit, loudly,
    because its cache writes would silently drop. Under the default
    ``"paged"`` layout only what can NEVER fit rejects (raw prompt +
    budget vs ``max_len``, worst-case blocks vs the whole pool); a
    request that merely cannot fit right now is admitted and DEFERRED
    at tick time — re-queued at the head, retried as slots retire and
    free their blocks. ``kv_block_size``/``kv_blocks`` size the paged
    pool (default: the dense worst case, so the default engine never
    defers where dense admitted); ``prefill_chunk`` bounds the prompt
    tokens prefilled per tick (pin via arg or
    ``SPARKDL_TPU_PREFILL_CHUNK``).

    ``auto_start=False`` exposes :meth:`tick` for deterministic
    single-step tests; the default runs the loop on a daemon thread.

    ``chain_tokens`` fuses up to k decode steps into ONE device dispatch
    (``lax.scan`` over the donated cache — runtime/dispatch.py): a
    decode step is tiny next to the per-dispatch gap, so the unchained
    loop pays a full dispatch *per generated token*. Chaining trades
    admission/retirement granularity (checks run every k tokens, not
    every token) for k-fold dispatch amortization; k is re-bounded every
    tick by the smallest remaining token budget in flight (the earliest
    possible retirement — nothing is decoded past it) and by the
    tightest in-flight deadline over the measured per-token time, so
    p99 latency does not regress. Greedy tokens are identical at any k.
    None = auto-calibrate from the dispatch gap; 1 (default) = one
    token per dispatch, the exact pre-chaining tick semantics.

    ``sp`` (paged layout; pin via ``SPARKDL_TPU_SP``) spreads each
    prefill chunk across that many chips and stages the prompt's K/V
    in a sequence-sharded pool (``sp_kv_blocks`` sizes it; default =
    the decode pool rounded up to divide ``sp``). Power of two, at
    most the visible device count. Decode is untouched: one handoff
    gather per admission. None/1 (default) = off.

    ``spec_k`` (paged layout) turns on speculative decoding: up to
    ``spec_k - 1`` draft tokens per slot (from ``draft_source``,
    default radix-trie + n-gram — :mod:`serving.spec_decode`) are
    verified by one L=k target-model dispatch; accepted tokens are
    bitwise-identical to plain decode, and the verify width shrinks
    under the same budget/deadline caps as ``chain_tokens`` plus the
    measured acceptance rate. None (default) = off. ``kv_dtype``
    ("fp32" | "bf16" | "int8") picks the paged pool's storage layout;
    quantize/dequantize are fused into the paged programs and compute
    stays at the model dtype.
    """

    def __init__(self, config, variables, *, n_slots: int = 8,
                 max_len: int = 512, max_queue_depth: int = 256,
                 eos_id: Optional[int] = None,
                 idle_wait_s: float = 0.005,
                 chain_tokens: "int | None" = 1,
                 kv_layout: str = "paged",
                 kv_block_size: int = 16,
                 kv_blocks: "int | None" = None,
                 prefill_chunk: "int | None" = None,
                 sp: "int | None" = None,
                 sp_kv_blocks: "int | None" = None,
                 spec_k: "int | None" = None,
                 draft_source: Any = None,
                 kv_dtype: str = "fp32",
                 host_kv_blocks: "int | None" = None,
                 disk_kv_blocks: "int | None" = None,
                 kv_spill_dir: "str | None" = None,
                 metrics: ServingMetrics | None = None,
                 slo: "slo_mod.SLO | None" = None,
                 tenants: "tenancy.TenantRegistry | None" = None,
                 host_id: "str | None" = None,
                 auto_start: bool = True):
        import jax
        import jax.numpy as jnp
        from jax import lax

        from sparkdl_tpu.models.gpt import (
            GPTLMHeadModel,
            init_block_pool,
            init_cache,
        )
        from sparkdl_tpu.runtime.batching import default_buckets

        if n_slots < 1:
            raise ValueError(f"n_slots must be >= 1, got {n_slots}")
        if chain_tokens is not None and chain_tokens < 1:
            raise ValueError(
                f"chain_tokens must be >= 1, got {chain_tokens}"
            )
        if kv_layout not in ("paged", "dense"):
            raise ValueError(
                f"kv_layout must be 'paged' or 'dense', got {kv_layout!r}"
            )
        if spec_k is not None and spec_k < 2:
            raise ValueError(
                f"spec_k must be >= 2 (one draft + its verify), got "
                f"{spec_k}; None disables speculative decoding"
            )
        if kv_layout != "paged" and (spec_k is not None
                                     or kv_dtype != "fp32"):
            raise ValueError(
                "speculative decoding (spec_k) and quantized KV pools "
                "(kv_dtype) require kv_layout='paged'; the dense layout "
                "is the exact parity oracle"
            )
        if kv_layout != "paged" and host_kv_blocks is not None:
            raise ValueError(
                "tiered KV (host_kv_blocks) requires kv_layout='paged': "
                "parking pages pool blocks, and the dense layout has "
                "no block pool"
            )
        if disk_kv_blocks is not None and host_kv_blocks is None:
            raise ValueError(
                "disk_kv_blocks requires host_kv_blocks: the disk tier "
                "sits below the host tier (blocks spill host->disk, "
                "never device->disk directly)"
            )
        if host_kv_blocks is not None and host_kv_blocks < 1:
            raise ValueError(
                f"host_kv_blocks must be >= 1, got {host_kv_blocks}")
        if disk_kv_blocks is not None and disk_kv_blocks < 0:
            raise ValueError(
                f"disk_kv_blocks must be >= 0, got {disk_kv_blocks}")
        if sp is not None and sp < 1:
            raise ValueError(f"sp must be >= 1, got {sp}")
        # Resolve the env pin HERE, before layout validation, so
        # SPARKDL_TPU_SP=2 on a dense-layout engine raises exactly like
        # sp=2 the argument would (pins are loud — a silently non-sp
        # engine is the failure mode resolve_pin exists to prevent).
        from sparkdl_tpu.ingest.pipeline import resolve_pin
        sp_val, _, _ = resolve_pin(sp, "SPARKDL_TPU_SP", 1, what="sp")
        if kv_layout != "paged" and sp_val > 1:
            raise ValueError(
                "sequence parallelism (sp) requires kv_layout='paged': "
                "the sp prefill stages K/V in a sequence-sharded block "
                "pool"
            )
        if (config.positions == "learned"
                and max_len > config.max_seq_len):
            raise ValueError(
                f"max_len {max_len} exceeds the learned position table "
                f"(max_seq_len={config.max_seq_len})"
            )
        from sparkdl_tpu.serving.metrics import default_host_id

        self.config = config
        self.variables = variables
        #: stable host identity for the fabric's router tier (ISSUE 14):
        #: snapshot()/capacity are keyed by it, the prefix digest names
        #: it, and SPARKDL_TPU_HOST_ID pins it per process
        self.host_id = host_id if host_id is not None else default_host_id()
        self.n_slots = n_slots
        self.max_len = max_len
        self.eos_id = eos_id
        self.idle_wait_s = idle_wait_s
        self.chain_tokens = chain_tokens
        self.kv_layout = kv_layout
        self.spec_k = spec_k
        self.kv_dtype = kv_dtype if kv_layout == "paged" else "fp32"
        self.sp = 1  # raised past 1 by _init_sp in the paged branch
        self._sp_handoffs = 0
        self._spec_policy = (SpecPolicy(max_k=spec_k)
                             if spec_k is not None else None)
        self._spec_dispatches = 0
        self._spec_proposed = 0
        self._spec_accepted = 0
        self._spec_tokens = 0
        self._spec_fallbacks = 0
        self._chain_policy = ChainPolicy(
            max_chain=chain_tokens if chain_tokens is not None else 32
        )
        if chain_tokens is None:
            # auto mode reads the gap per tick: calibrate once here,
            # outside the engine lock, never inside the decode loop
            self._chain_policy.gap()
        self.queue = RequestQueue(max_depth=max_queue_depth,
                                  tenants=tenants)
        #: next monotonic stamp the tick feeds the process brownout
        #: controller (bounded evaluation stride, not per-tick)
        self._overload_next = 0.0
        self.metrics = metrics if metrics is not None else ServingMetrics()
        self._model = GPTLMHeadModel(config)
        self._len_buckets = default_buckets(max_len, min_bucket=8)
        self._inflight: dict[int, _InFlight] = {}
        self._prefilling: dict[int, _Prefill] = {}
        self._last_tok = np.zeros((n_slots,), np.int32)
        self._prefill_seconds = 0.0
        self._prefill_chunks = 0
        self._deferrals = 0
        #: host/disk tier store for parked cold sessions (ROADMAP
        #: item 1); None = flat single-tier cache (the default)
        self._kv_tiers = None
        self._park_fallbacks = 0
        self._max_tick_prefill_tokens = 0
        self._prefill_rr = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

        model = self._model

        if kv_layout == "paged":
            from sparkdl_tpu.models.gpt import dequantize_kv, quantize_kv
            from sparkdl_tpu.serving.kv_blocks import KVBlockPool
            from sparkdl_tpu.serving.prefix_cache import PrefixCache
            from sparkdl_tpu.serving.spec_decode import (
                default_draft_source,
            )

            if kv_block_size < 1:
                raise ValueError(
                    f"kv_block_size must be >= 1, got {kv_block_size}")
            # default 256: the chunk is a decode-LATENCY bound (one
            # tick never prefills more than this many tokens), so it
            # should sit well ABOVE typical prompts — throttling every
            # cold admission to tiny chunks serializes admission for no
            # latency benefit. Shrink it when long prompts must not
            # stall live decode ticks.
            chunk, _, _ = resolve_pin(
                prefill_chunk, "SPARKDL_TPU_PREFILL_CHUNK", 256,
                what="prefill_chunk",
            )
            if chunk < 1:
                raise ValueError(
                    f"prefill_chunk must be >= 1, got {chunk}")
            self.prefill_chunk = chunk
            bs_kv = kv_block_size
            mb = -(-max_len // bs_kv)  # table width, blocks per sequence
            w = mb * bs_kv  # gathered virtual-cache width (>= max_len)
            # widest chunk PROGRAM ever built: chunks bucket to their
            # real token count, and no chunk carries more than a whole
            # prompt (<= w) even when the per-tick budget is larger
            self._chunk_cap = min(chunk, w)
            # private prefill cache is one max-width chunk wider than
            # the table span: a chunk write must never clamp
            wp = w + self._chunk_cap
            if kv_blocks is None:
                # default pool = the dense layout's worst case, so the
                # default engine can never defer where dense admitted;
                # shrink kv_blocks to make memory the real bound
                kv_blocks = n_slots * mb
            if kv_blocks < 1:
                raise ValueError(
                    f"kv_blocks must be >= 1, got {kv_blocks}")
            self._kv_bs = bs_kv
            self._mb = mb
            self._w = w
            self._wp = wp
            if kv_dtype != "fp32":
                # the bring-up of a COMPRESSED pool is a distinct
                # failure surface (scale buffers, storage casts) the
                # chaos harness must reach: an injected kv.quantize
                # fault fails construction loudly BEFORE any
                # process-wide registration leaks (gauges register
                # below, EngineObservability last)
                fault_point("kv.quantize")
            self._pool = KVBlockPool(kv_blocks, bs_kv, dtype=kv_dtype)
            #: which pool the last deferral was short on (_defer reads
            #: it; the sp staging branch points it at _sp_pool)
            self._defer_pool = self._pool
            if host_kv_blocks is not None:
                from sparkdl_tpu.serving.kv_tiers import TieredKVStore

                # disk overflow may only drop trie LEAVES — dropping
                # an interior parked node would orphan its (parked)
                # descendants' payloads
                self._kv_tiers = TieredKVStore(
                    host_kv_blocks, disk_kv_blocks or 0,
                    spill_dir=kv_spill_dir,
                    is_droppable=lambda node: not node.children)
            self._prefix = PrefixCache(self._pool,
                                       tiers=self._kv_tiers)
            self._draft = (draft_source if draft_source is not None
                           else default_draft_source(self._prefix))
            self._pool_kv = init_block_pool(config, kv_blocks, bs_kv,
                                            dtype=kv_dtype)
            # block tables: one row per slot, sentinel (= kv_blocks)
            # marks empty entries — gather clips it, scatter drops it
            self._table = np.full((n_slots, mb), self._pool.sentinel,
                                  np.int32)
            self._pidx = np.zeros((n_slots,), np.int32)
            n_layers = config.num_layers
            nh = config.num_heads
            hd = config.hidden_size // config.num_heads
            max_pos = (config.max_seq_len - 1
                       if config.positions == "learned" else wp + chunk)
            cdt = config.dtype

            # The dtype boundary, fused into every paged program: the
            # pool is the only compressed tensor — compute (attention,
            # private prefill caches) always runs at the model dtype.
            # int8 carries one fp32 scale per written column
            # (models.gpt.quantize_kv), riding the block structure in
            # pool["k_scale"]/["v_scale"].
            def _dq_gather(pool, name, ids):
                # pool[name][:, ids] in storage dtype -> compute dtype
                x = pool[name][:, ids]
                if kv_dtype == "int8":
                    return dequantize_kv(
                        x, pool[name + "_scale"][:, ids], cdt)
                return x if kv_dtype == "fp32" else x.astype(cdt)

            def _q_write(pool, where, newk, newv):
                # THE quantize-on-write path (every pool write goes
                # through here, so scatter and install can never
                # desynchronize): ``where`` is the advanced index after
                # the layer axis — (blk, off) column tuples for decode/
                # verify scatter, (ids,) whole blocks for the prefill
                # install. int8 writes values + their per-column scales;
                # sentinel entries drop — no block corrupted.
                ix = (slice(None),) + where
                out = dict(pool)
                for name, vals in (("k", newk), ("v", newv)):
                    if kv_dtype == "int8":
                        q, s = quantize_kv(vals)
                        out[name] = pool[name].at[ix].set(
                            q, mode="drop")
                        sc = name + "_scale"
                        out[sc] = pool[sc].at[ix].set(s, mode="drop")
                    else:
                        out[name] = pool[name].at[ix].set(
                            vals.astype(pool[name].dtype), mode="drop")
                return out

            def _q_scatter(pool, blk, off, newk, newv):
                # freshly written columns; blk/off share any index
                # shape ([S] decode, [S,k] verify)
                return _q_write(pool, (blk, off), newk, newv)

            @functools.partial(jax.jit, donate_argnums=(1,),
                               static_argnums=(5, 6))
            def _paged_step(variables, pool, table, idx, tok, k, nb):
                # k tokens for every slot over the BLOCK TABLE: each
                # step gathers the table's blocks into a virtual dense
                # [S, nb*bs] cache (same math as the dense layout, so
                # greedy tokens stay bitwise-identical), runs the
                # per-slot decode, then scatters the one written column
                # back to its pool block. ``nb`` (static, bucketed) is
                # the block count covering the DEEPEST live row through
                # this chain — the gather and attention touch only the
                # live head of the table, often FEWER columns than the
                # dense layout's fixed max_len (masked-width invariance
                # keeps tokens bitwise). Rows are right-aligned (no
                # left pad: column i holds real token i), so the causal
                # mask alone masks garbage columns and positions need
                # no start offset. Sentinel table entries clip on
                # gather (masked garbage) and drop on scatter (no block
                # corrupted).
                sub = table[:, :nb]

                def body(carry, _):
                    pool, idx, tok = carry
                    kbuf = _dq_gather(pool, "k", sub).reshape(
                        n_layers, n_slots, nb * bs_kv, nh, hd)
                    vbuf = _dq_gather(pool, "v", sub).reshape(
                        n_layers, n_slots, nb * bs_kv, nh, hd)
                    cache = {"k": kbuf, "v": vbuf, "idx": idx}
                    logits, cache = model.apply(
                        variables, tok[:, None], cache=cache,
                    )
                    ntok = jnp.argmax(logits[:, -1], axis=-1)
                    rows = jnp.arange(n_slots)
                    blk = table[rows, idx // bs_kv]
                    off = idx % bs_kv
                    newk = cache["k"][:, rows, idx]
                    newv = cache["v"][:, rows, idx]
                    pool = _q_scatter(pool, blk, off, newk, newv)
                    return (pool, idx + 1, ntok), ntok

                (pool, _, _), toks = lax.scan(
                    body, (pool, idx, tok), None, length=k
                )
                return toks, pool

            @functools.partial(jax.jit, donate_argnums=(1,),
                               static_argnums=(5, 6))
            def _paged_verify(variables, pool, table, idx, toks, k, nb):
                # Speculative verify: score a k-token span for every
                # slot in ONE dispatch. Column 0 of ``toks`` is each
                # slot's current last token, columns 1.. its proposed
                # drafts; the L=k per-slot step (models/gpt.py) writes
                # all k columns at [idx[s], idx[s]+k) and the per-row
                # causal mask conditions position j on the real context
                # plus drafts [:j] — exactly the logits greedy
                # acceptance needs, same gather/scatter shape as
                # _paged_step so greedy tokens stay bitwise. Columns of
                # REJECTED drafts scatter back as garbage PAST the
                # accepted frontier (the host advances pidx only over
                # accepted inputs): they sit causally masked until the
                # next dispatch's own writes overwrite them — the same
                # garbage-but-finite contract as retired-slot columns.
                sub = table[:, :nb]
                kbuf = _dq_gather(pool, "k", sub).reshape(
                    n_layers, n_slots, nb * bs_kv, nh, hd)
                vbuf = _dq_gather(pool, "v", sub).reshape(
                    n_layers, n_slots, nb * bs_kv, nh, hd)
                cache = {"k": kbuf, "v": vbuf, "idx": idx}
                logits, cache = model.apply(variables, toks, cache=cache)
                out = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                rows = jnp.arange(n_slots)[:, None]
                pos = idx[:, None] + jnp.arange(k)[None, :]
                blk = table[rows, pos // bs_kv]
                off = pos % bs_kv
                newk = cache["k"][:, rows, pos]
                newv = cache["v"][:, rows, pos]
                return out, _q_scatter(pool, blk, off, newk, newv)

            def _gathered(pool, ids):
                # cached-prefix blocks -> the head of a private prefill
                # cache (the copy that makes partial-block sharing
                # copy-on-write: the sharer re-installs into blocks it
                # owns, the donor block is never written). Sentinel ids
                # clip to garbage the chunked prefill masks/overwrites.
                # Quantized pools dequantize here: the private cache is
                # compute-dtype, and the final install requantizes —
                # an exact round trip (quantize_kv absmax maps to ±127),
                # so a COW-shared block re-installs bit-identical to its
                # donor.
                kx = _dq_gather(pool, "k", ids).reshape(
                    n_layers, 1, w, nh, hd)
                vx = _dq_gather(pool, "v", ids).reshape(
                    n_layers, 1, w, nh, hd)
                pad = ((0, 0), (0, 0), (0, wp - w), (0, 0), (0, 0))
                return jnp.pad(kx, pad), jnp.pad(vx, pad)

            def _chunk_apply(variables, ck, cv, idx, ids, cols):
                # one bounded prefill chunk, right-aligned: writes K/V
                # at columns [idx, idx+width) of the private cache,
                # where width = ids.shape[1] is the POWER-OF-2 BUCKET of
                # this chunk's real token count (same compile-reuse
                # trick as the dense path's prompt buckets: a 24-token
                # suffix pays a 32-wide program, not a chunk-cap-wide
                # one). ``cols`` (static, bucketed >= idx+width) bounds
                # the attention to the LIVE head of the buffer — every
                # column past it is causally masked garbage anyway, so
                # slicing changes nothing but the wasted FLOPs. The tail
                # of the chunk is zero-padded on the right; pad queries
                # produce garbage columns PAST every real position, so
                # the causal mask hides them until real writes overwrite
                # them — no attention_mask needed (vs the dense path's
                # left-pad masking).
                positions = jnp.minimum(
                    idx + jnp.arange(ids.shape[1])[None, :], max_pos)
                cache = {"k": ck[:, :, :cols], "v": cv[:, :, :cols],
                         "idx": idx}
                logits, cache = model.apply(
                    variables, ids, cache=cache, positions=positions,
                )
                ck = ck.at[:, :, :cols].set(cache["k"])
                cv = cv.at[:, :, :cols].set(cache["v"])
                return logits, ck, cv

            def _installed(pool, ck, cv, ids):
                # private prefill cache -> the slot's OWNED pool blocks
                # (quantize-on-install rides the shared _q_write path).
                # ids carries the sentinel at shared-prefix positions
                # (their content already lives in the shared blocks) and
                # past the covered span: those writes drop.
                kv = ck[:, 0, :w].reshape(n_layers, mb, bs_kv, nh, hd)
                vv = cv[:, 0, :w].reshape(n_layers, mb, bs_kv, nh, hd)
                return _q_write(pool, (ids,), kv, vv)

            # Four fused chunk programs so a prefill pays the minimum
            # dispatch count (dispatch gap dominates small programs —
            # the ISSUE 3 lesson applied to admission): the FIRST chunk
            # fuses the prefix gather, the FINAL chunk fuses the block
            # install, so a suffix that fits one chunk is ONE device
            # dispatch end to end (vs dense's prefill + scatter pair).
            @functools.partial(jax.jit, donate_argnums=(1,),
                               static_argnums=(6,))
            def _chunk_one(variables, pool, gids, idx, ids, inst, cols):
                ck, cv = _gathered(pool, gids)
                logits, ck, cv = _chunk_apply(
                    variables, ck, cv, idx, ids, cols)
                return logits, _installed(pool, ck, cv, inst)

            @functools.partial(jax.jit, static_argnums=(5,))
            def _chunk_first(variables, pool, gids, idx, ids, cols):
                ck, cv = _gathered(pool, gids)
                return _chunk_apply(variables, ck, cv, idx, ids, cols)

            @functools.partial(jax.jit, donate_argnums=(1, 2),
                               static_argnums=(5,))
            def _chunk_mid(variables, ck, cv, idx, ids, cols):
                return _chunk_apply(variables, ck, cv, idx, ids, cols)

            # (ck/cv are deliberately NOT donated here or in _chunk_one:
            # no output shares their shape, so donation could not alias
            # — jax would warn "donated buffers were not usable" on
            # every compile and free nothing earlier; they die on the
            # host right after the call regardless)
            @functools.partial(jax.jit, donate_argnums=(1,),
                               static_argnums=(7,))
            def _chunk_final(variables, pool, ck, cv, idx, ids, inst,
                             cols):
                logits, ck, cv = _chunk_apply(
                    variables, ck, cv, idx, ids, cols)
                return logits, _installed(pool, ck, cv, inst)

            @jax.jit
            def _park_fetch(pool, ids):
                # the D2H half of a park: the given blocks' RAW
                # storage-dtype bytes (int8 codes + their scales, no
                # dequantize) — raw is both the 4x cheaper transfer
                # the quantized layout bought and what makes a resumed
                # session bitwise-identical: unpark writes back the
                # exact bytes decode would have read
                out = {"k": pool["k"][:, ids], "v": pool["v"][:, ids]}
                if kv_dtype == "int8":
                    out["k_scale"] = pool["k_scale"][:, ids]
                    out["v_scale"] = pool["v_scale"][:, ids]
                return out

            @functools.partial(jax.jit, donate_argnums=(0,))
            def _unpark_install(pool, ids, payload):
                # the H2D half of a resume: whole-block raw writes
                # into freshly allocated blocks (sentinel ids drop —
                # same contract as every other pool write)
                out = dict(pool)
                for name, vals in payload.items():
                    out[name] = pool[name].at[:, ids].set(
                        vals.astype(pool[name].dtype), mode="drop")
                return out

            self._paged_step_fn = _paged_step
            self._paged_verify_fn = _paged_verify
            self._chunk_one_fn = _chunk_one
            self._chunk_first_fn = _chunk_first
            self._chunk_mid_fn = _chunk_mid
            self._chunk_final_fn = _chunk_final
            self._park_fetch_fn = _park_fetch
            self._unpark_install_fn = _unpark_install
            # the sp handoff/prefix programs reuse the dtype boundary
            self._dq_gather_fn = _dq_gather
            self._q_write_fn = _q_write
            if sp_val > 1:
                self._init_sp(sp_val, sp_kv_blocks)
        else:
            self._cache = init_cache(
                config, n_slots, max_len, per_slot=True)
            self._start = np.zeros((n_slots,), np.int32)

        @jax.jit
        def _prefill(variables, ids, mask):
            # batch-1 left-padded prefill in a fresh scalar-idx cache of
            # the SHARED buffer width, so columns line up at scatter time.
            # jit's shape cache gives one compile per prompt-length bucket.
            lp = ids.shape[1]
            cache = init_cache(config, 1, max_len)
            positions = jnp.clip(jnp.cumsum(mask, axis=1) - 1, 0)
            key_valid = jnp.concatenate(
                [mask.astype(bool),
                 jnp.ones((1, max_len - lp), bool)], axis=1,
            )
            logits, cache = model.apply(
                variables, ids, cache=cache, positions=positions,
                attention_mask=key_valid,
            )
            return jnp.argmax(logits[:, -1], axis=-1), cache

        # donate the cache through scatter and step: the engine always
        # discards the old version, and without donation every token
        # would materialize a second full [layers, S, max_len, H, D]
        # buffer (2x HBM peak + a copy per token at serving sizes)
        @functools.partial(jax.jit, donate_argnums=(0,))
        def _scatter(cache, row, slot):
            # install a prefilled row into slot (traced index: one compile)
            return {
                "k": jax.lax.dynamic_update_slice_in_dim(
                    cache["k"], row["k"], slot, axis=1),
                "v": jax.lax.dynamic_update_slice_in_dim(
                    cache["v"], row["v"], slot, axis=1),
                "idx": cache["idx"].at[slot].set(
                    row["idx"].astype(jnp.int32)),
            }

        @functools.partial(jax.jit, donate_argnums=(1,))
        def _step(variables, cache, tok, start):
            # one token for every slot; the per-slot cache gives each row
            # its own causal depth, `start` masks its left-pad columns,
            # and RoPE/learned positions count real tokens only
            positions = (cache["idx"] - start)[:, None]
            key_valid = jnp.arange(max_len)[None, :] >= start[:, None]
            logits, cache = model.apply(
                variables, tok[:, None], cache=cache, positions=positions,
                attention_mask=key_valid,
            )
            return jnp.argmax(logits[:, -1], axis=-1), cache

        @functools.partial(jax.jit, donate_argnums=(1,),
                           static_argnums=(3,))
        def _step_chain(variables, cache, tok, k, start):
            # k tokens per dispatch: scan the single-step body carrying
            # (cache, tok) — each step's argmax feeds the next, exactly
            # the unchained sequence, amortizing the dispatch gap k-fold.
            # The carried cache IS the iteration dependence (no CSE
            # collapse possible) and rides the donated input buffer.
            def body(carry, _):
                cache, tok = carry
                positions = (cache["idx"] - start)[:, None]
                key_valid = (jnp.arange(max_len)[None, :]
                             >= start[:, None])
                logits, cache = model.apply(
                    variables, tok[:, None], cache=cache,
                    positions=positions, attention_mask=key_valid,
                )
                tok = jnp.argmax(logits[:, -1], axis=-1)
                return (cache, tok), tok

            (cache, _), toks = lax.scan(
                body, (cache, tok), None, length=k
            )
            return toks, cache

        self._prefill_fn = _prefill
        self._scatter_fn = _scatter
        self._step_fn = _step
        self._step_chain_fn = _step_chain
        # process-wide registrations go LAST: a constructor failure above
        # (bad config, cache init OOM) must not leak a tracker/provider
        # bound to a half-built engine
        from sparkdl_tpu.serving.metrics import EngineObservability

        self._obs = EngineObservability(
            "continuous", self._flight_context, slo=slo, n_slots=n_slots)
        self.slo_tracker = self._obs.tracker
        if auto_start:
            self.start()

    # -- sequence-parallel prefill (ISSUE 13 / ROADMAP item 2) ---------------
    def _init_sp(self, sp: int, sp_kv_blocks: "int | None") -> None:
        """Spatial prefill chunks: a dp=1 mesh over the first ``sp``
        local devices, a sequence-sharded STAGING pool (block axis on
        the ``sp`` mesh axis, placed through the partitioner's
        ``KV_POOL_RULES``), and explicit-sharding chunk programs whose
        QUERIES are sharded over ``sp`` — each chip embeds and projects
        its contiguous token shard, GSPMD all-gathers the chunk's K/V
        for the causal attention (the all-gather schedule of
        ``models.gpt.sp_prefill``; the ring rotation is the large-sp /
        on-chip variant), so one tick's chunk runs across ``sp`` chips
        instead of one. The staging pool holds the accumulating prompt
        K/V between ticks (sharded — a long context never has to fit
        one chip); decode stays on the untouched single-device paged
        path, fed by ONE gather at the prefill→decode handoff
        (``sp.gather`` fault site).

        Staging stores the COMPUTE dtype even under quantized decode
        pools: chunks then attend over exact K/V (bitwise-identical to
        the sp=1 private-cache path) and the handoff install quantizes
        ONCE — exactly where the single-device install does.
        """
        import functools

        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        from sparkdl_tpu.models.gpt import init_block_pool
        from sparkdl_tpu.partition.mesh_factory import make_mesh
        from sparkdl_tpu.partition.rules import (
            KV_POOL_RULES,
            match_partition_rules,
            sequence_activation_spec,
        )
        from sparkdl_tpu.serving.kv_blocks import SeqShardedBlockPool

        if sp & (sp - 1):
            raise ValueError(
                f"sp must be a power of two (chunk widths bucket to "
                f"powers of two and shard evenly), got {sp}")
        devs = jax.devices()
        if sp > len(devs):
            raise ValueError(
                f"sp={sp} exceeds the {len(devs)} visible devices")
        self.sp = sp
        # every chunk-program width (pow2_bucket clamped to _chunk_cap)
        # must SHARD EVENLY over sp — a non-divisible cap (prefill_chunk
        # not a multiple of sp, or an odd table span) would crash the
        # first full-width dispatch on the ids in_sharding. Floor the
        # cap to a multiple of sp (never below sp) and clamp the
        # per-tick budget under it (a tick must never stage more real
        # tokens than one program can carry).
        self._chunk_cap = max(sp, (self._chunk_cap // sp) * sp)
        self.prefill_chunk = min(self.prefill_chunk, self._chunk_cap)
        config = self.config
        model = self._model
        bs_kv = self._kv_bs
        n_layers, nh = config.num_layers, config.num_heads
        hd = config.hidden_size // nh
        max_pos = (config.max_seq_len - 1
                   if config.positions == "learned"
                   else self._wp + self.prefill_chunk)
        mesh = make_mesh(dp=1, sp=sp, devices=devs[:sp])
        self._sp_mesh = mesh
        n_sp = (sp_kv_blocks if sp_kv_blocks is not None
                else self._pool.n_blocks)
        n_sp = -(-n_sp // sp) * sp  # shard the block axis evenly
        # staged-head span with CHUNK HEADROOM: a prefix hit offsets
        # the chunk grid, so the final chunk's bucketed width can cross
        # the table-span boundary (c0 + wc up to w - 1 + chunk_cap) —
        # without the headroom the model's cached write would silently
        # clamp, exactly the overflow the non-sp private cache sizes
        # wp = w + chunk_cap against
        self._mb_sp = -(-(self._w + self._chunk_cap) // bs_kv)
        self._sp_pool = SeqShardedBlockPool(n_sp, bs_kv, sp)
        sp_tree = init_block_pool(config, n_sp, bs_kv)
        specs = match_partition_rules(KV_POOL_RULES, sp_tree)
        pool_sh = jax.tree_util.tree_map(
            lambda s: NamedSharding(mesh, s), specs)
        # sparkdl-lint: disable=lock-discipline -- constructor path: the engine loop thread does not exist until auto_start, so no concurrent reader
        self._sp_pool_kv = jax.device_put(sp_tree, pool_sh)
        rep = NamedSharding(mesh, P())
        ids_sh = NamedSharding(
            mesh, sequence_activation_spec(ndim=2, seq_dim=1))
        logits_sh = NamedSharding(
            mesh, sequence_activation_spec(ndim=3, seq_dim=1))
        # host-side arithmetic for sparkdl_sp_permute_bytes_total: each
        # chip contributes its K/V chunk shard to sp-1 peers
        self._sp_bytes_per_col = (
            2 * n_layers * config.hidden_size
            * np.dtype(config.dtype).itemsize * (sp - 1))

        @functools.partial(
            jax.jit, donate_argnums=(1,), static_argnums=(7,),
            in_shardings=(rep, pool_sh, rep, rep, ids_sh, rep, rep),
            out_shardings=(logits_sh, pool_sh))
        def _sp_chunk(variables, sppool, head, idx, ids, sblk, soff,
                      nbh):
            # One SPATIAL prefill chunk: gather the staged head
            # (sentinels clip to causally-masked garbage), write this
            # chunk's K/V into it through the model's cached path —
            # queries sharded over sp, K all-gathered by GSPMD for the
            # dense masked softmax, so logits are bitwise-identical to
            # the single-device chunk — then scatter the freshly
            # written columns back to their staged blocks (sentinel
            # targets drop: pad columns never land).
            wc = ids.shape[1]
            kbuf = sppool["k"][:, head].reshape(
                n_layers, 1, nbh * bs_kv, nh, hd)
            vbuf = sppool["v"][:, head].reshape(
                n_layers, 1, nbh * bs_kv, nh, hd)
            positions = jnp.minimum(
                idx + jnp.arange(wc)[None, :], max_pos)
            cache = {"k": kbuf, "v": vbuf, "idx": idx}
            logits, cache = model.apply(
                variables, ids, cache=cache, positions=positions)
            newk = jax.lax.dynamic_slice_in_dim(
                cache["k"][:, 0], idx, wc, axis=1)
            newv = jax.lax.dynamic_slice_in_dim(
                cache["v"][:, 0], idx, wc, axis=1)
            ix = (slice(None), sblk, soff)
            out = dict(sppool)
            out["k"] = sppool["k"].at[ix].set(newk, mode="drop")
            out["v"] = sppool["v"].at[ix].set(newv, mode="drop")
            return logits, out

        @functools.partial(
            jax.jit, donate_argnums=(0,),
            in_shardings=(pool_sh, rep, rep, rep),
            out_shardings=pool_sh)
        def _sp_seed(sppool, kdata, vdata, ids):
            # cached-prefix K/V -> the staged blocks backing the hit
            # span (the prefix gather, sharded along the same axis):
            # whole-block writes, sentinel targets drop
            out = dict(sppool)
            out["k"] = sppool["k"].at[:, ids].set(kdata, mode="drop")
            out["v"] = sppool["v"].at[:, ids].set(vdata, mode="drop")
            return out

        @functools.partial(
            jax.jit,
            in_shardings=(pool_sh, rep), out_shardings=(rep, rep))
        def _sp_gather(sppool, ids):
            # prefill->decode handoff: the request's staged blocks,
            # gathered ONCE across the sp shards (replicated out; the
            # host hop to the single-device decode pool is the
            # documented boundary between the two device worlds)
            return sppool["k"][:, ids], sppool["v"][:, ids]

        _dq = self._dq_gather_fn
        _qw = self._q_write_fn

        @jax.jit
        def _sp_prefix_fetch(pool, gids):
            # cached prefix blocks out of the DECODE pool, dequantized
            # to the compute dtype (the same values the single-device
            # first chunk gathers into its private cache)
            return _dq(pool, "k", gids), _dq(pool, "v", gids)

        @functools.partial(jax.jit, donate_argnums=(0,))
        def _sp_install(pool, kdata, vdata, inst):
            # the handoff install into the decode pool's owned blocks:
            # the same _q_write path as the fused single-device install
            # (sentinels at shared-prefix positions drop; quantized
            # pools quantize HERE, once)
            return _qw(pool, (inst,), kdata, vdata)

        self._sp_chunk_fn = _sp_chunk
        self._sp_seed_fn = _sp_seed
        self._sp_gather_fn = _sp_gather
        self._sp_prefix_fetch_fn = _sp_prefix_fetch
        self._sp_install_fn = _sp_install

    # -- submission ----------------------------------------------------------
    def submit(self, prompt_ids, max_new_tokens: int, *,
               timeout_s: float | None = None,
               tenant: str = "default",
               priority: "int | None" = None) -> Future:
        """Admit one prompt; Future resolves to the generated ids
        (np.int32 array, ``<= max_new_tokens`` long — shorter on eos).

        ``tenant``/``priority`` scope the request for quota, DRR
        weight, and class scheduling (ISSUE 20) — the defaults are the
        bitwise-compatible single-user path. See
        :meth:`RequestQueue.submit` for the typed admission rejects
        (``TenantThrottledError``/``BrownoutShedError``)."""
        from sparkdl_tpu.runtime.batching import pick_bucket

        prompt = np.asarray(prompt_ids, np.int32)
        if prompt.ndim != 1 or prompt.size == 0:
            raise ValueError(
                f"prompt must be a non-empty 1-D id array, got shape "
                f"{prompt.shape}"
            )
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}"
            )
        if self.kv_layout == "paged":
            # the paged layout stores tokens unpadded, so the true
            # per-request bound is the RAW length (dense pays the
            # prompt-length bucket) — and the pool: a request whose
            # worst-case block count exceeds the whole pool can never
            # fit and is rejected loudly; one that merely cannot fit
            # NOW is admitted and deferred at tick time.
            if len(prompt) + max_new_tokens > self.max_len:
                raise ValueError(
                    f"prompt {len(prompt)} + max_new_tokens "
                    f"{max_new_tokens} exceeds cache max_len "
                    f"{self.max_len}: raise max_len or shorten the "
                    "request"
                )
            need = -(-(len(prompt)
                       + self._admission_budget_tokens(max_new_tokens))
                     // self._kv_bs)
            if need > self._pool.n_blocks:
                raise ValueError(
                    f"request needs {need} KV blocks but the pool holds "
                    f"{self._pool.n_blocks}: it can never fit — raise "
                    "kv_blocks or shorten the request"
                )
            if self.sp > 1:
                nbp = -(-len(prompt) // self._kv_bs)
                if nbp > self._sp_pool.n_blocks:
                    raise ValueError(
                        f"prompt needs {nbp} staging blocks but the "
                        f"sp pool holds {self._sp_pool.n_blocks}: it "
                        "can never prefill — raise sp_kv_blocks or "
                        "shorten the prompt"
                    )
        else:
            lp = pick_bucket(len(prompt), self._len_buckets)
            if lp + max_new_tokens > self.max_len:
                raise ValueError(
                    f"prompt bucket {lp} + max_new_tokens "
                    f"{max_new_tokens} exceeds cache max_len "
                    f"{self.max_len}: raise max_len or shorten the "
                    "request"
                )
        return self.queue.submit(
            GenRequest(prompt, max_new_tokens), timeout_s=timeout_s,
            tenant=tenant, priority=priority,
        )

    def _admission_budget_tokens(self, max_new_tokens: int) -> int:
        """Decode-side tokens a paged admission must reserve blocks for
        beyond the prompt. The colocated engine reserves the FULL token
        budget up front (decode can never hit mid-stream exhaustion);
        a prefill-tier worker (:mod:`sparkdl_tpu.disagg`) overrides this
        to 0 — it only ever holds prompt K/V, the decode tier owns the
        generation span."""
        return max_new_tokens

    # -- engine loop ---------------------------------------------------------
    def start(self) -> "ContinuousGPTEngine":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="sparkdl-continuous-gpt", daemon=True
            )
            self._thread.start()
        return self

    def close(self, *, drain: bool = True,
              timeout_s: float | None = 30.0) -> None:
        """Stop. ``drain=True`` finishes every admitted request (queued
        and in-flight) first; ``drain=False`` fails them now."""
        self.queue.close()
        if not drain:
            self.queue.fail_pending()
            self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout_s)
        elif drain:  # manual-tick mode: drain inline
            while (self.queue.depth > 0 or self._inflight
                   or self._prefilling):
                self.tick()
        self._stop.set()
        # join timeout or a crashed loop may leave requests queued: no
        # Future may ever be left unresolved
        self.queue.fail_pending()
        with self._lock:
            self._fail_inflight(EngineClosedError("engine shut down"))
        self._obs.close(drain=drain)
        if self.kv_layout == "paged":
            self._pool.close()
            if self.sp > 1:
                self._sp_pool.close()
            if self._kv_tiers is not None:
                self._kv_tiers.close()

    def begin_drain(self) -> "list[Request]":
        """Graceful host drain, phase one (ISSUE 14): stop admission and
        hand back every request that was accepted but NOT yet placed in
        a slot — the fabric re-queues them onto surviving hosts
        (``RequestQueue.requeue`` on the target; trace ids, deadlines,
        and Futures ride the returned :class:`Request` objects
        untouched). Requests already prefilling/decoding are NOT
        returned: they finish here — the engine loop exits on its own
        once the last one retires, after which :meth:`close` joins
        instantly. Idempotent-ish: a second call returns []."""
        self.queue.close()
        reqs = self.queue.extract_pending()
        flight_mod.record_event(
            "engine.drain_begin", engine=getattr(self._obs, "name", None),
            host=self.host_id, extracted=len(reqs),
            inflight=len(self._inflight) + len(self._prefilling))
        return reqs

    def reopen(self) -> "ContinuousGPTEngine":
        """Reverse :meth:`begin_drain` (ISSUE 16): accept submits again
        and, if the loop thread exited on graceful drain, restart it —
        the spare-host rejoin path (an AutoScaler that parked a drained
        handle puts it back in service through ``Router.add_host``).
        Only for engines that were DRAINED, never CLOSED: close() tears
        down pools and observability, which do not come back."""
        self._stop.clear()
        self.queue.reopen()
        t = self._thread
        if t is not None and not t.is_alive():
            self._thread = None
            self.start()
        return self

    def prefix_digest(self, max_entries: int = 1024) -> "dict | None":
        """The compact prefix→host digest this host publishes
        (ISSUE 14): chained hashes of its cached block-aligned prompt
        prefixes, most-recently-used first, bounded. A router matches an
        incoming prompt's own block hashes against these to estimate
        how many prefill blocks this host already holds. None under the
        dense layout (no prefix cache — nothing to be affine to)."""
        if self.kv_layout != "paged":
            return None
        with self._lock:
            # version is the prefix cache's membership-mutation counter
            # (ISSUE 19), NOT a per-publish sequence: two wholesale
            # fetches with no traffic between them carry the same
            # version, and a delta whose ``since`` matches it replays
            # exactly the mutations this snapshot missed.
            return {
                "host_id": self.host_id,
                "block_size": self._kv_bs,
                "version": self._prefix.digest_version,
                "hashes": self._prefix.block_hashes(max_entries),
            }

    def prefix_digest_delta(self, since_version: int,
                            max_entries: int = 1024) -> "dict | None":
        """Membership adds/evictions since ``since_version`` — the
        steady-state digest refresh (ISSUE 19): a router tracking this
        host pulls KBs of deltas instead of re-shipping the wholesale
        digest every interval. ``None`` = gap (the caller fell behind
        the bounded journal, or claims a future version): refresh
        wholesale. The ``digest.delta`` fault site models a torn delta
        read — the router answers any error here the same way, with a
        wholesale re-sync."""
        if self.kv_layout != "paged":
            return None
        with self._lock:
            fault_point("digest.delta")
            delta = self._prefix.block_hash_delta(
                int(since_version), max_entries)
            if delta is None:
                return None
            delta["host_id"] = self.host_id
            delta["block_size"] = self._kv_bs
            return delta

    def _loop(self) -> None:
        try:
            while not self._stop.is_set():
                did_work = self.tick()
                if self.queue.closed and not did_work:
                    with self._lock:
                        if (self.queue.depth == 0 and not self._inflight
                                and not self._prefilling):
                            return  # graceful drain complete
            # non-graceful: surviving inflight failed by close()
        except BaseException as e:
            # a crashed loop (device OOM, XLA error) must not strand
            # callers blocked on their Futures
            exc = (e if isinstance(e, Exception)
                   else EngineClosedError(f"engine loop died: {e!r}"))
            self.queue.close()
            self.queue.fail_pending(exc)
            with self._lock:
                self._fail_inflight(exc)
            raise

    # -- one scheduling quantum ---------------------------------------------
    def tick(self) -> bool:
        """Admit into free slots, advance chunked prefills by at most
        ``prefill_chunk`` tokens, advance every live row one token,
        retire finished rows. Returns True if any work happened (False =
        idle tick). Thread-safe; the background loop is just
        ``while True: tick()``."""
        with self._lock:
            now = time.monotonic()
            self._overload_tick(now)
            self._expire_inflight(now)
            free = [s for s in range(self.n_slots)
                    if s not in self._inflight
                    and s not in self._prefilling]
            if not free and self._prefilling:
                # saturated with a background prefill in flight: a more
                # urgent waiting class may claim its slot (ISSUE 20)
                if self._maybe_preempt(now):
                    free = [s for s in range(self.n_slots)
                            if s not in self._inflight
                            and s not in self._prefilling]
            if free:
                wait = (0.0 if self._inflight or self._prefilling
                        else self.idle_wait_s)
                reqs = self.queue.take(len(free), wait)
                deferred = False
                for i, req in enumerate(reqs):
                    slot = free.pop(0)
                    try:
                        admitted = self._admit(slot, req)
                    except Exception as e:
                        # take() already moved this Future to RUNNING, so
                        # nobody else can resolve it: a failed admission
                        # (prefill OOM, compile error) is THIS request's
                        # error, never the engine's — the slot stays free
                        # and the loop keeps serving
                        free.insert(0, slot)
                        self._fail_request(req, e, tokens=0)
                        continue
                    if not admitted:
                        # pool exhausted: defer this request AND every
                        # later one taken this tick back to the queue
                        # head, in order — deferral never reorders
                        # accepted traffic (a later arrival must not
                        # grab the blocks the deferred one is owed)
                        free.insert(0, slot)
                        self._defer(reqs[i:])
                        deferred = True
                        break
                if (not deferred and self.kv_layout == "paged"
                        and (self._pool.deferral_streak
                             or (self.sp > 1
                                 and self._sp_pool.deferral_streak))):
                    # free slots existed and nothing deferred this tick
                    # (the deferred work admitted, or left the queue —
                    # e.g. expired): the exhaustion episode is over. A
                    # streak must never outlive the pressure, or an
                    # idle, recovered engine would read degraded
                    # forever and the next real incident would miss its
                    # postmortem trigger. (The pool also clears the
                    # streak itself whenever release() frees blocks.)
                    self._pool.reset_deferral_streak()
                    if self.sp > 1:
                        self._sp_pool.reset_deferral_streak()
            else:
                self.queue.sweep_expired()  # deadlines don't wait for slots
            did_work = False
            if self._prefilling:
                self._prefill_tick()
                did_work = True
            if self._inflight:
                self._decode_step()
                did_work = True
            return did_work

    def _defer(self, reqs: "list[Request]") -> None:
        """KV pool exhaustion: re-queue in order, count the streak ON
        THE POOL THAT ACTUALLY DEFERRED (``_admit_paged`` marks
        ``_defer_pool`` — decode pool or the sp staging pool; a staging
        stall recorded against the decode pool would read healthy and
        never trip the postmortem), and after ``_EXHAUST_DUMP_STREAK``
        consecutive deferrals hand the flight recorder a postmortem
        trigger (providers capture the pool state). Self-recovering:
        blocks free as slots retire."""
        self.queue.requeue(reqs)
        self._deferrals += 1
        gen: GenRequest = reqs[0].payload
        pool = self._defer_pool
        staging = pool is not self._pool
        # the recovery bar: worst-case blocks of the request being owed
        # (ignores prefix-cache sharing — a conservative overestimate,
        # so a partial free can never clear a streak the request's
        # admission would still defer on). Staging holds prompt blocks
        # only; the decode pool the full prompt + budget span.
        span = (len(gen.prompt) if staging
                else len(gen.prompt)
                + self._admission_budget_tokens(gen.max_new_tokens))
        pool.record_deferral(need=-(-span // self._kv_bs))
        streak = pool.deferral_streak
        flight_mod.record_event(
            "kv.admission_deferred",
            engine=getattr(self._obs, "name", None),
            request_id=reqs[0].request_id,
            deferred=len(reqs),
            streak=streak,
            pool="sp_staging" if staging else "decode",
            blocks_free=pool.free_count,
            blocks_total=pool.n_blocks,
        )
        if streak == _EXHAUST_DUMP_STREAK:
            flight_mod.trigger_dump(
                "kv.pool_exhausted",
                streak=streak,
                pool="sp_staging" if staging else "decode",
                blocks_total=pool.n_blocks,
            )

    def _maybe_preempt(self, now: float) -> bool:
        """Priority preemption between prefill chunks (ISSUE 20): with
        every slot busy and a strictly more urgent class waiting, tear
        down the LEAST urgent background prefill and re-queue its
        request at its own class head — zero lost. Only requests in
        the background class (priority >= PRIORITY_BACKGROUND) are
        preemptible, and only BETWEEN chunks (mid-dispatch state never
        exists at tick boundaries). The victim's pool references go
        back through the prefix cache, so its already-registered
        prefix blocks stay cached (and parkable via the kv_tiers
        path): the re-run prefills only what the cache cannot serve.
        The ``tenant.preempt`` fault site fires before teardown; an
        injected fault still re-queues the victim (chaos contract) —
        it only suppresses the slot handover this tick. Returns True
        when a slot was freed. Called under the engine lock."""
        waiting = self.queue.highest_waiting_priority()
        if waiting is None:
            return False
        slot, st = max(self._prefilling.items(),
                       key=lambda kv: kv[1].req.priority)
        if (st.req.priority < tenancy.PRIORITY_BACKGROUND
                or waiting >= st.req.priority):
            return False
        fault: "Exception | None" = None
        try:
            fault_point("tenant.preempt")
        except Exception as e:
            fault = e
        # the same teardown discipline as _sp_abort: drop the prefill
        # record, release staging + every pool reference, THEN requeue
        # — on the fault path too, so the victim is never lost
        del self._prefilling[slot]
        self._release_sp_staging(st)
        self._prefix.release(st.all_blocks())
        if fault is None:
            tenancy.note_preemption()
            flight_mod.record_event(
                "tenant.preempted",
                request_id=st.req.request_id, tenant=st.req.tenant,
                victim_priority=st.req.priority,
                waiting_priority=waiting,
                prefilled=st.pos, prompt_tokens=len(st.prompt))
        else:
            flight_mod.record_event(
                "tenant.preempt_failed",
                error=type(fault).__name__,
                request_id=st.req.request_id, tenant=st.req.tenant)
        self.queue.requeue([st.req])
        return fault is None

    def _overload_tick(self, now: float) -> None:
        """Feed the process brownout controller (when installed) this
        engine's overload signals — worst SLO burn rate across
        dimensions plus queue fill fraction — on a bounded stride, so
        the ladder's hysteresis counts wall-clock-ish evaluations, not
        raw tick rate. No controller installed = zero work (the
        bitwise default path)."""
        ctrl = tenancy.process_overload()
        if ctrl is None or now < self._overload_next:
            return
        self._overload_next = now + _OVERLOAD_STRIDE_S
        burn = None
        if self.slo_tracker is not None:
            rep = self.slo_tracker.sample()
            burns = [d["burn_rate"] for d in
                     (rep.get("latency"), rep.get("availability"))
                     if isinstance(d, dict)]
            if burns:
                burn = max(burns)
        ctrl.evaluate(
            burn_rate=burn,
            queue_frac=self.queue.depth / self.queue.max_depth)

    def _admit(self, slot: int, req: Request) -> bool:
        """Place one taken request into ``slot``. Returns False when the
        paged block pool cannot back it right now (caller defers)."""
        if self.kv_layout == "paged":
            return self._admit_paged(slot, req)
        self._admit_dense(slot, req)
        return True

    def _admit_dense(self, slot: int, req: Request) -> None:
        import jax.numpy as jnp

        from sparkdl_tpu.runtime.batching import pick_bucket

        gen: GenRequest = req.payload
        lp = pick_bucket(len(gen.prompt), self._len_buckets)
        t0 = time.perf_counter()
        with span("serving.prefill", parent=req.trace_ctx,
                  prompt_len=len(gen.prompt), bucket=lp, slot=slot,
                  request_id=req.request_id):
            ids = np.zeros((1, lp), np.int32)
            mask = np.zeros((1, lp), np.int32)
            ids[0, lp - len(gen.prompt):] = gen.prompt
            mask[0, lp - len(gen.prompt):] = 1
            tok, row = self._prefill_fn(
                self.variables, jnp.asarray(ids), jnp.asarray(mask)
            )
            self._cache = self._scatter_fn(
                self._cache, row, jnp.asarray(slot, jnp.int32)
            )
            first = int(tok[0])
        self._prefill_seconds += time.perf_counter() - t0
        self._start[slot] = lp - len(gen.prompt)
        self._last_tok[slot] = first
        flight = _InFlight(req, [first], gen.max_new_tokens)
        self._inflight[slot] = flight
        if self._is_done(flight):  # max_new_tokens=1, or instant eos
            self._complete(slot)

    # -- paged admission + chunked prefill -----------------------------------
    def _admit_paged(self, slot: int, req: Request) -> bool:
        """Match the longest cached prefix, allocate the request's
        worst-case remaining blocks up front (so decode can never hit
        mid-stream exhaustion), and queue the suffix for chunked
        prefill. False = pool exhausted right now (defer)."""
        import jax.numpy as jnp

        gen: GenRequest = req.payload
        prompt = np.asarray(gen.prompt, np.int32)
        plen = len(prompt)
        toks = tuple(int(t) for t in prompt)
        nb_total = -(-(plen
                       + self._admission_budget_tokens(gen.max_new_tokens))
                     // self._kv_bs)
        # turn resume: page any parked prefix of this prompt back in
        # BEFORE matching, so the match below sees device blocks and
        # the resume costs one H2D copy per block instead of a
        # re-prefill. Restored blocks hold a temporary reference
        # (restore allocation may demote OTHER cold leaves, never
        # these) released as soon as match has taken its own.
        restored: "list[int]" = []
        if self._kv_tiers is not None:
            restored = self._prefix.restore_path(
                toks[:-1], alloc_block=self._alloc_one_block,
                install=self._install_parked)
            self._update_unpark_reserved()
            if restored:
                flight_mod.record_event(
                    "kv.unparked", request_id=req.request_id,
                    blocks=len(restored))
        # the last prompt token must always prefill — the cache holds
        # K/V, not the logits that seed decode
        m = self._prefix.match(toks[:-1])
        if restored:
            self._prefix.release(restored)
        matched = (m.full_blocks
                   + ([m.partial_block] if m.partial_block is not None
                      else []))
        try:
            owned = self._alloc_blocks(nb_total - len(m.full_blocks))
        except Exception as e:
            # an injected kv.alloc fault (chaos harness) or allocator
            # error is exhaustion, not a request error: defer, recover
            flight_mod.record_event(
                "kv.alloc_error", error=type(e).__name__,
                request_id=req.request_id)
            owned = None
        if owned is None:
            self._prefix.release(matched)
            self._defer_pool = self._pool
            return False
        # the first chunk will gather the cached prefix into the private
        # prefill cache (also the COW copy of a partial tail block);
        # sentinel entries are masked garbage, so no-hit = fresh cache.
        # The partial block keeps its extra reference until that gather
        # has been DISPATCHED (releasing it now would let an eviction +
        # realloc overwrite it before the copy).
        gids = np.full((self._mb,), self._pool.sentinel, np.int32)
        gids[:len(m.full_blocks)] = m.full_blocks
        if m.partial_block is not None:
            gids[len(m.full_blocks)] = m.partial_block
        n_shared = len(m.full_blocks)
        inst = np.full((self._mb,), self._pool.sentinel, np.int32)
        inst[n_shared:n_shared + len(owned)] = owned
        sp_blocks = None
        cow = m.partial_block
        if self.sp > 1:
            # sequence-parallel staging: the prompt's K/V accumulate in
            # sp-sharded blocks (striped across chips), allocated up
            # front like the decode blocks — exhaustion defers
            try:
                sp_blocks = self._sp_pool.allocate(
                    -(-plen // self._kv_bs))
            except Exception as e:
                # an injected kv.alloc fault on the STAGING allocate is
                # exhaustion too — defer, never fail the request (and
                # never leak the decode blocks already taken above)
                flight_mod.record_event(
                    "kv.alloc_error", error=type(e).__name__,
                    request_id=req.request_id)
                sp_blocks = None
            if sp_blocks is None:
                # staging exhausted: same deferral contract as the
                # decode pool — the caller's _defer records the streak
                # on the STAGING pool (the one actually short)
                self._prefix.release(matched + owned)
                self._defer_pool = self._sp_pool
                return False
            if m.full_blocks or cow is not None:
                try:
                    self._sp_seed_prefix(gids, sp_blocks,
                                         len(m.full_blocks)
                                         + (cow is not None))
                except Exception:
                    self._sp_pool.release(
                        self._sp_pool.deref(sp_blocks))
                    self._prefix.release(matched + owned)
                    raise
                if cow is not None:
                    # the COW copy is dispatched into the staged block:
                    # the sp chunks never read the decode pool again, so
                    # the partial tail's extra hold can drop now
                    self._prefix.release([cow])
                    cow = None
        self._prefix.record_lookup(m.hit_tokens, plen - m.hit_tokens)
        if m.hit_tokens:
            flight_mod.record_event(
                "kv.prefix_hit", request_id=req.request_id,
                hit_tokens=m.hit_tokens, prompt_tokens=plen)
        self._prefilling[slot] = _Prefill(
            req=req, prompt=prompt, max_new=gen.max_new_tokens,
            pos=m.hit_tokens, hit=m.hit_tokens,
            shared=m.full_blocks, owned=owned,
            gather_ids=gids, install_ids=inst,
            cow_block=cow, sp_blocks=sp_blocks,
        )
        self._pool.reset_deferral_streak()
        if self.sp > 1:
            self._sp_pool.reset_deferral_streak()
        return True

    def _sp_seed_prefix(self, gids: np.ndarray, sp_blocks: "list[int]",
                        n_hit_blocks: int) -> None:
        """Copy the matched prefix span (full blocks + COW partial
        tail) from the decode pool into the staged blocks backing it —
        one dequantizing fetch, one sharded seed scatter."""
        import jax.numpy as jnp

        seed = np.full((self._mb,), self._sp_pool.sentinel, np.int32)
        seed[:n_hit_blocks] = sp_blocks[:n_hit_blocks]
        kd, vd = self._sp_prefix_fetch_fn(
            self._pool_kv, jnp.asarray(gids))
        self._sp_pool_kv = self._sp_seed_fn(
            self._sp_pool_kv, np.asarray(kd), np.asarray(vd),
            jnp.asarray(seed))

    def _alloc_blocks(self, n: int) -> "list[int] | None":
        got = self._pool.allocate(n)
        if got is None:
            short = n - self._pool.free_count
            if self._kv_tiers is not None:
                # tiered: page cold leaves OUT (device->host->disk)
                # instead of discarding them — the demoted sessions
                # resume with one H2D copy, not a re-prefill
                freed = self._prefix.demote(short, self._park_payload)
                self._update_unpark_reserved()
            else:
                freed = self._prefix.evict(short)
            if freed >= short:
                got = self._pool.allocate(n)
        return got

    def _alloc_one_block(self) -> "int | None":
        got = self._alloc_blocks(1)
        return got[0] if got else None

    # -- tiered park/resume (ROADMAP item 1) ----------------------------------
    def _park_payload(self, bid: int) -> "dict | None":
        """D2H-fetch one cold block's raw bytes for parking. None =
        torn park (injected ``kv.park`` fault or transfer failure):
        the caller falls back to plain eviction — the session simply
        re-prefills next turn, nothing is lost."""
        import jax.numpy as jnp

        from sparkdl_tpu.runtime.completion import start_fetch
        from sparkdl_tpu.serving import kv_tiers as kv_tiers_mod

        t0 = time.monotonic()
        try:
            fault_point("kv.park")
            tree = self._park_fetch_fn(
                self._pool_kv, jnp.asarray([bid], jnp.int32))
            ticket = start_fetch(tree, path="kv_park")
            # sparkdl-lint: disable=blocking-in-hot-loop -- a park only runs when allocation already came up short, and the copy is one block (the alternative, plain eviction, costs that session a full re-prefill)
            fetched = ticket.result()
        except Exception as e:
            self._park_fallbacks += 1
            kv_tiers_mod._M_FALLBACKS.inc(op="park")
            flight_mod.record_event(
                "kv.park_failed", error=type(e).__name__, block=bid)
            return None
        payload = {name: np.asarray(v)[:, 0]
                   for name, v in fetched.items()}
        kv_tiers_mod._M_PARK_SEC.observe(time.monotonic() - t0)
        return payload

    def _install_parked(self, bid: int, payload: dict) -> bool:
        """H2D-install one parked block's raw bytes into a fresh pool
        block. False = corrupt unpark (injected ``kv.unpark`` fault):
        the caller prunes the parked subtree and the suffix
        re-prefills — the request still completes."""
        import jax.numpy as jnp

        from sparkdl_tpu.serving import kv_tiers as kv_tiers_mod

        t0 = time.monotonic()
        try:
            fault_point("kv.unpark")
            tree = {name: jnp.asarray(np.asarray(v)[:, None])
                    for name, v in payload.items()}
            # sparkdl-lint: disable=lock-discipline -- only reachable from _admit_paged's restore_path callback, which the admission loop enters holding self._lock
            self._pool_kv = self._unpark_install_fn(
                self._pool_kv, jnp.asarray([bid], jnp.int32), tree)
        except Exception as e:
            # sparkdl-lint: disable=lock-discipline -- same reach as the install above: restore_path's caller (_admit_paged) already holds self._lock
            self._park_fallbacks += 1
            kv_tiers_mod._M_FALLBACKS.inc(op="unpark")
            flight_mod.record_event(
                "kv.unpark_failed", error=type(e).__name__, block=bid)
            return False
        kv_tiers_mod._M_UNPARK_SEC.observe(time.monotonic() - t0)
        return True

    def _update_unpark_reserved(self) -> None:
        """Tell the pool how many free blocks parked state expects to
        claim on resume, so the autoscaler's shrink defers instead of
        stranding unparks behind re-prefills (capped at the pool —
        over-subscription past that is already a full pool)."""
        if self._kv_tiers is None:
            return
        s = self._kv_tiers.stats()
        self._pool.unpark_reserved = min(
            s["host_blocks"] + s["disk_blocks"], self._pool.n_blocks)

    def park_cold(self, max_blocks: "int | None" = None) -> int:
        """Explicitly page every currently cold cached block out to
        the host tier (benches/tests; production parks lazily under
        allocation pressure). Returns device blocks freed. Refcounted
        shares and partial-block COW donors never park."""
        if self._kv_tiers is None:
            raise RuntimeError(
                "park_cold needs a host tier: construct the engine "
                "with host_kv_blocks")
        with self._lock:
            n = (max_blocks if max_blocks is not None
                 else self._prefix.cached_blocks)
            freed = self._prefix.demote(
                n, self._park_payload, evict_fallback=False)
            self._update_unpark_reserved()
            return freed

    # -- parked-session migration (ISSUE 19) ----------------------------------
    def export_parked_sessions(self,
                               max_sessions: "int | None" = None
                               ) -> "dict | None":
        """Serialize every parked session's block-aligned prefix path
        for re-parking on another host — the drain/scale-down tail of
        ROADMAP item 1: without this, parked state strands on the host
        that parked it and every idle conversation re-prefills cold.
        Each session ships its WHOLE path (device-resident ancestors
        are D2H-fetched like a park; parked blocks are peeked from
        their tier) through the handoff raw-storage codec, so the
        importing host resumes bitwise-identically. Exported parked
        subtrees are pruned here — the state now lives on the target;
        a torn export (``kv.migrate`` fault) skips that session, which
        simply re-prefills on resume (never lost, never duplicated).
        None when this engine has no tier store."""
        if self.kv_layout != "paged" or self._kv_tiers is None:
            return None
        from sparkdl_tpu.disagg.handoff import _enc
        from sparkdl_tpu.serving import kv_tiers as kv_tiers_mod

        t0 = time.monotonic()
        sessions: "list[dict]" = []
        with self._lock:
            paths = self._prefix.parked_leaf_paths()
            if max_sessions is not None:
                paths = paths[:int(max_sessions)]
            prune: "list[Any]" = []
            for tokens, nodes in paths:
                try:
                    fault_point("kv.migrate")
                    blocks = []
                    for n in nodes:
                        pl = (self._park_payload(n.block_id)
                              if n.tier == "device"
                              else self._kv_tiers.peek(n))
                        if pl is None:
                            raise RuntimeError(
                                "torn export: block payload unavailable")
                        blocks.append(
                            {k: _enc(np.asarray(v))
                             for k, v in pl.items()})
                except Exception as e:
                    kv_tiers_mod._M_MIGRATIONS.inc(outcome="export_failed")
                    flight_mod.record_event(
                        "kv.migrate_export_failed", host=self.host_id,
                        error=type(e).__name__)
                    continue
                sessions.append({"tokens": [int(t) for t in tokens],
                                 "blocks": blocks})
                kv_tiers_mod._M_MIGRATIONS.inc(outcome="exported")
                kv_tiers_mod._M_MIG_BLOCKS.inc(len(blocks))
                top = next(
                    (n for n in nodes if n.tier != "device"), None)
                if top is not None:
                    prune.append(top)
            seen: "set[int]" = set()
            for top in prune:
                # tops are roots of maximal parked subtrees — disjoint,
                # but two leaves under one top share it: prune once
                if id(top) in seen:
                    continue
                seen.add(id(top))
                self._prefix._prune_parked(top)
            self._update_unpark_reserved()
        kv_tiers_mod._M_MIG_SEC.observe(time.monotonic() - t0)
        flight_mod.record_event(
            "kv.migrate_export", host=self.host_id,
            sessions=len(sessions))
        return {"host_id": self.host_id, "block_size": self._kv_bs,
                "kv_dtype": self.kv_dtype, "sessions": sessions}

    def import_parked_sessions(self, bundle: "dict | None") -> int:
        """Adopt migrated parked sessions into this host's tier store
        (the receiving end of :meth:`export_parked_sessions`): each
        session's blocks re-park here and its trie path is grafted in,
        so the next turn's ``restore_path`` pages it in with one H2D
        per block instead of a re-prefill. Sessions on a different
        block grid or storage dtype are skipped whole (their bytes
        cannot install here — re-prefill is the correct fallback), as
        is any session torn by the ``kv.migrate`` fault site. Returns
        sessions adopted."""
        if (self.kv_layout != "paged" or self._kv_tiers is None
                or not bundle):
            return 0
        from sparkdl_tpu.disagg.handoff import _dec
        from sparkdl_tpu.serving import kv_tiers as kv_tiers_mod

        if int(bundle.get("block_size") or 0) != self._kv_bs:
            return 0
        dtype = bundle.get("kv_dtype")
        if dtype is not None and str(dtype) != str(self.kv_dtype):
            return 0
        t0 = time.monotonic()
        adopted = 0
        with self._lock:
            for sess in bundle.get("sessions") or ():
                try:
                    fault_point("kv.migrate")
                    blocks = [{k: _dec(v) for k, v in b.items()}
                              for b in sess["blocks"]]
                    toks = tuple(int(t) for t in sess["tokens"])
                    if len(toks) != len(blocks) * self._kv_bs:
                        raise ValueError("ragged migration payload")
                    self._prefix.adopt_parked(toks, blocks)
                except Exception as e:
                    kv_tiers_mod._M_MIGRATIONS.inc(
                        outcome="import_failed")
                    flight_mod.record_event(
                        "kv.migrate_import_failed", host=self.host_id,
                        error=type(e).__name__)
                    continue
                adopted += 1
                kv_tiers_mod._M_MIGRATIONS.inc(outcome="imported")
            self._update_unpark_reserved()
        kv_tiers_mod._M_MIG_SEC.observe(time.monotonic() - t0)
        flight_mod.record_event(
            "kv.migrate_import", host=self.host_id, sessions=adopted)
        return adopted

    def _prefill_tick(self) -> None:
        """Advance chunked prefills by at most ``prefill_chunk`` REAL
        tokens this tick, round-robin across prefilling slots — the
        bound that keeps a long prompt from freezing in-flight decode
        latency (several short prompts fit one tick's budget; a long
        one takes exactly one chunk per tick)."""
        budget = self.prefill_chunk
        slots = sorted(self._prefilling)
        if len(slots) > 1:
            pivot = self._prefill_rr % len(slots)
            slots = slots[pivot:] + slots[:pivot]
        self._prefill_rr += 1
        tick_tokens = 0
        for slot in slots:
            st = self._prefilling[slot]
            r = min(self.prefill_chunk, len(st.prompt) - st.pos)
            if r > budget:
                continue  # over this tick's budget: next tick
            budget -= r
            tick_tokens += r
            self._prefill_chunk_step(slot, st, r)
            if budget <= 0:
                break
        self._max_tick_prefill_tokens = max(
            self._max_tick_prefill_tokens, tick_tokens)

    def _prefill_chunk_step(self, slot: int, st: _Prefill,
                            r: int) -> None:
        import jax.numpy as jnp

        if st.sp_blocks is not None:
            self._sp_chunk_step(slot, st, r)
            return
        c0 = st.pos
        first = st.ck is None
        final = c0 + r == len(st.prompt)
        from sparkdl_tpu.runtime.batching import pow2_bucket

        # chunk-program width: power-of-2 bucket of the real token
        # count (capped by the budget) — compile reuse without paying
        # the full budget width for a short suffix
        wc = pow2_bucket(r, 8, self._chunk_cap)
        ids = np.zeros((1, wc), np.int32)
        ids[0, :r] = st.prompt[c0:c0 + r]
        # static attention width: bucket of the live buffer head — the
        # program attends over [0, cols) instead of the whole private
        # cache (everything past idx+wc is causally masked garbage)
        cols = pow2_bucket(c0 + wc, 8, self._wp)
        idx = jnp.asarray(c0, jnp.int32)
        ids = jnp.asarray(ids)
        t0 = time.perf_counter()
        with span("serving.prefill_chunk", parent=st.req.trace_ctx,
                  request_id=st.req.request_id, slot=slot,
                  start=c0, tokens=r, first=first, final=final):
            if first and final:
                logits, self._pool_kv = self._chunk_one_fn(
                    self.variables, self._pool_kv,
                    jnp.asarray(st.gather_ids), idx, ids,
                    jnp.asarray(st.install_ids), cols)
            elif first:
                logits, st.ck, st.cv = self._chunk_first_fn(
                    self.variables, self._pool_kv,
                    jnp.asarray(st.gather_ids), idx, ids, cols)
            elif final:
                logits, self._pool_kv = self._chunk_final_fn(
                    self.variables, self._pool_kv, st.ck, st.cv,
                    idx, ids, jnp.asarray(st.install_ids), cols)
                st.ck = st.cv = None
            else:
                logits, st.ck, st.cv = self._chunk_mid_fn(
                    self.variables, st.ck, st.cv, idx, ids, cols)
        if first and st.cow_block is not None:
            # the gather is dispatched: the COW copy is sequenced before
            # any later overwrite of the source block — drop the hold
            self._prefix.release([st.cow_block])
            st.cow_block = None
        st.pos += r
        st.chunks += 1
        self._prefill_chunks += 1
        _M_PREFILL_CHUNKS.inc()
        if final:
            # the chunk's last REAL column seeds decode (argmax on
            # device: the same op the oracle's generate uses)
            self._finish_prefill(slot, st, int(jnp.argmax(logits[0, r - 1])))
        self._prefill_seconds += time.perf_counter() - t0

    def _finish_prefill(self, slot: int, st: _Prefill,
                        first: int) -> None:
        n_shared = len(st.shared)
        nb_total = n_shared + len(st.owned)
        row = np.full((self._mb,), self._pool.sentinel, np.int32)
        row[:n_shared] = st.shared
        row[n_shared:nb_total] = st.owned
        self._table[slot] = row
        plen = len(st.prompt)
        n_prompt_blocks = -(-plen // self._kv_bs)
        self._prefix.register(
            tuple(int(t) for t in st.prompt),
            [int(b) for b in row[:n_prompt_blocks]],
        )
        self._pidx[slot] = plen
        self._last_tok[slot] = first
        del self._prefilling[slot]
        flight = _InFlight(st.req, [first], st.max_new,
                           blocks=st.shared + st.owned,
                           prompt=st.prompt)
        self._inflight[slot] = flight
        if self._is_done(flight):  # max_new_tokens=1, or instant eos
            self._complete(slot)

    # -- sequence-parallel chunk dispatch + handoff ---------------------------
    def _sp_chunk_step(self, slot: int, st: _Prefill, r: int) -> None:
        """One SPATIAL prefill chunk (sp > 1): ``r`` real tokens
        dispatched across the sp chips — queries sharded, K/V
        all-gathered, staged blocks scattered back sharded. The final
        chunk triggers the prefill→decode handoff. Dispatches record
        under ``sparkdl_dispatch_seconds{path="sp_prefill"}`` and NEVER
        feed the ChainPolicy: its calibrated dispatch gap is measured
        on single-device programs, and a collective-bearing dispatch
        would skew the auto-K the decode loop calibrates from."""
        import jax.numpy as jnp

        from sparkdl_tpu.runtime.batching import pow2_bucket

        try:
            # the injectable stand-in for a failed collective hop
            # (ring permute / all-gather): fires BEFORE the dispatch so
            # the donated staging pool is never half-consumed — the
            # chaos contract re-queues the victim, losing nothing
            fault_point("sp.permute")
        except Exception as e:
            self._sp_abort(slot, st, "sp.permute", e)
            return
        c0 = st.pos
        final = c0 + r == len(st.prompt)
        bs = self._kv_bs
        wc = pow2_bucket(r, max(8, self.sp), self._chunk_cap)
        ids = np.zeros((1, wc), np.int32)
        ids[0, :r] = st.prompt[c0:c0 + r]
        # staged head covering [0, c0+wc): bucketed block count for
        # compile reuse; sentinel where the prompt span ends. The cap
        # is _mb_sp (table span + chunk headroom), NOT _mb: a
        # hit-offset final chunk can reach past the table span, and a
        # clamped cached write would corrupt real columns
        nbh = pow2_bucket(-(-(c0 + wc) // bs), 1, self._mb_sp)
        head = np.full((nbh,), self._sp_pool.sentinel, np.int32)
        n_have = min(len(st.sp_blocks), nbh)
        head[:n_have] = st.sp_blocks[:n_have]
        # scatter targets for this chunk's columns; pad columns (>= r)
        # go to the sentinel and drop
        cols = c0 + np.arange(wc)
        sblk = np.full((wc,), self._sp_pool.sentinel, np.int32)
        real = np.arange(wc) < r
        sblk[real] = np.asarray(st.sp_blocks, np.int32)[
            cols[real] // bs]
        soff = (cols % bs).astype(np.int32)
        t0 = time.perf_counter()
        with span("serving.sp_prefill_chunk", parent=st.req.trace_ctx,
                  request_id=st.req.request_id, slot=slot, start=c0,
                  tokens=r, sp=self.sp, final=final):
            logits, self._sp_pool_kv = self._sp_chunk_fn(
                self.variables, self._sp_pool_kv, jnp.asarray(head),
                jnp.asarray(c0, jnp.int32), jnp.asarray(ids),
                jnp.asarray(sblk), jnp.asarray(soff), int(nbh))
        record_dispatch("sp_prefill", 1, time.perf_counter() - t0)
        _M_SP_RING_STEPS.inc(self.sp - 1)
        _M_SP_PERMUTE_BYTES.inc(self._sp_bytes_per_col * wc)
        st.pos += r
        st.chunks += 1
        self._prefill_chunks += 1
        _M_PREFILL_CHUNKS.inc()
        if final:
            first = int(jnp.argmax(logits[0, r - 1]))
            if self._sp_handoff(slot, st):
                self._finish_prefill(slot, st, first)
        self._prefill_seconds += time.perf_counter() - t0

    def _sp_handoff(self, slot: int, st: _Prefill) -> bool:
        """Prefill→decode handoff: gather the request's staged K/V once
        across the sp shards and install it into the decode pool's
        owned blocks — after this the per-token loop is EXACTLY the
        single-device paged path. Returns False when the ``sp.gather``
        fault site fired (request re-queued, nothing lost)."""
        import jax.numpy as jnp

        try:
            fault_point("sp.gather")
        except Exception as e:
            self._sp_abort(slot, st, "sp.gather", e)
            return False
        gids = np.full((self._mb,), self._sp_pool.sentinel, np.int32)
        gids[:len(st.sp_blocks)] = st.sp_blocks
        with span("serving.sp_handoff", parent=st.req.trace_ctx,
                  request_id=st.req.request_id, sp=self.sp):
            kd, vd = self._sp_gather_fn(
                self._sp_pool_kv, jnp.asarray(gids))
            # host hop: the staged world is mesh-committed, the decode
            # pool single-device — one bounded copy per ADMISSION, not
            # per token
            self._pool_kv = self._sp_install_fn(
                self._pool_kv, np.asarray(kd), np.asarray(vd),
                jnp.asarray(st.install_ids))
        self._sp_handoffs += 1
        self._release_sp_staging(st)
        return True

    def _sp_abort(self, slot: int, st: _Prefill, site: str,
                  exc: Exception) -> None:
        """A collective fault mid-sp-prefill: tear the prefill down,
        release every block it holds (staging AND decode pool), and
        re-queue the request at the head — zero lost admitted
        requests; the typed error lands in the flight ring."""
        del self._prefilling[slot]
        self._release_sp_staging(st)
        self._prefix.release(st.all_blocks())
        err = SpCollectiveError(f"{site} failed: {exc!r}")
        flight_mod.record_event(
            "sp.collective_failed", site=site,
            error=type(err).__name__, cause=type(exc).__name__,
            request_id=st.req.request_id, sp=self.sp,
            prefilled=st.pos, prompt_tokens=len(st.prompt))
        self.queue.requeue([st.req])

    def _release_sp_staging(self, st: _Prefill) -> None:
        if st.sp_blocks:
            self._sp_pool.release(self._sp_pool.deref(st.sp_blocks))
            st.sp_blocks = None

    def _release_slot(self, slot: int,
                      blocks: "list[int] | None") -> None:
        """Return a retiring slot's table to sentinel and drop its block
        references (registered prompt blocks stay cached for prefix
        reuse; the rest free)."""
        if self.kv_layout != "paged":
            return
        self._table[slot] = self._pool.sentinel
        self._pidx[slot] = 0
        if blocks:
            self._prefix.release(blocks)

    def _bounded_tokens(self, now: float, cap: int) -> int:
        """Clamp a per-dispatch token count to (a) the smallest
        remaining token budget in flight — the earliest possible
        retirement, so no slot is held past its scheduled exit and no
        decoded token is wasted on budget grounds — and (b) the tightest
        in-flight deadline over the measured per-token time (2x safety),
        so a request never expires inside a dispatch it could have
        survived. Shared by the chained decode AND the speculative
        verify width — budget/deadline semantics cannot drift between
        the two."""
        cap = min(cap, *(
            f.max_new - len(f.produced) for f in self._inflight.values()
        ))
        tok_s = self._chain_policy.program_s
        if tok_s:
            for f in self._inflight.values():
                if f.req.deadline is not None:
                    headroom = (f.req.deadline - now) / (2.0 * tok_s)
                    cap = min(cap, int(headroom))
        elif any(f.req.deadline is not None
                 for f in self._inflight.values()):
            # no per-token estimate yet and a deadline is in flight: the
            # first dispatch doubles as the measurement probe at k=1 so
            # a request can never expire inside an unmeasured chain
            return 1
        return cap

    def _decode_chain_len(self, now: float) -> int:
        """Tokens to fuse into the next plain decode dispatch: the
        configured/auto cap under the shared budget/deadline bound,
        rounded down to a power of two — at most log2(cap) compiled
        chain programs ever exist."""
        if tenancy.overload_level() >= tenancy.LEVEL_DEGRADE:
            return 1  # brownout: shed chained-decode burstiness first
        cap = (self.chain_tokens if self.chain_tokens is not None
               else self._chain_policy.chain_len())
        cap = self._bounded_tokens(now, cap)
        if cap <= 1:
            return 1
        return 1 << (cap.bit_length() - 1)

    def _spec_width(self, now: float) -> int:
        """Verify width (1 + drafts) for the next speculative dispatch:
        the configured ``spec_k`` cap shrunk by the measured acceptance
        rate (SpecPolicy — wasted verify positions are real FLOPs) and
        the same budget/deadline bound as ``chain_tokens``, so a
        deadline-tight stream degrades to plain single-token decode
        mid-flight instead of expiring inside a wide verify. Power of
        two: {2,4,8,...} compiled verify programs, never one per width.
        """
        if tenancy.overload_level() >= tenancy.LEVEL_DEGRADE:
            return 1  # brownout: wasted verify FLOPs are shed first
        cap = min(self.spec_k, self._spec_policy.spec_len())
        cap = self._bounded_tokens(now, cap)
        if cap < 2:
            return 1
        return 1 << (cap.bit_length() - 1)

    def _spec_step(self) -> bool:
        """One propose -> verify -> accept quantum. Returns True when a
        verify dispatch advanced the batch (the tick's decode is done);
        False when speculation stood down this tick — width bounded
        below 2, no proposer had a draft, or the ``spec.verify`` fault
        site fired (the chaos contract: a failed verify falls back to
        plain decode, zero lost requests)."""
        import jax
        import jax.numpy as jnp

        from sparkdl_tpu.runtime.batching import pow2_bucket
        from sparkdl_tpu.serving.spec_decode import greedy_accept

        k = self._spec_width(time.monotonic())
        if k < 2:
            return False
        # propose per live slot (ids only, host-side): context is
        # prompt + produced. Slots whose proposer stands down ride the
        # dispatch with filler drafts — the verify batch is all
        # n_slots wide regardless, rejection costs them nothing, and
        # an accidental filler match is by construction the argmax
        # (i.e. a correct token).
        drafts = np.zeros((self.n_slots, k - 1), np.int32)
        real_len: "dict[int, int]" = {}
        proposed = 0
        for slot, f in self._inflight.items():
            ctx = np.concatenate(
                [f.prompt, np.asarray(f.produced, np.int32)])
            got = self._draft.propose(ctx, k - 1)[:k - 1]
            real_len[slot] = len(got)
            proposed += len(got)
            if got:
                drafts[slot, :len(got)] = got
        if not proposed:
            return False
        try:
            # the injectable stand-in for a failed verify dispatch: it
            # fires BEFORE the jitted call so the donated pool is never
            # half-consumed, and the tick serves everyone through the
            # plain decode path instead
            fault_point("spec.verify")
        except Exception as e:
            self._spec_fallbacks += 1
            _M_SPEC_FALLBACKS.inc()
            flight_mod.record_event(
                "spec.verify_failed",
                engine=getattr(self._obs, "name", None),
                error=type(e).__name__, k=k,
                slots=len(self._inflight))
            return False
        toks = np.concatenate(
            [np.asarray(self._last_tok[:, None], np.int32), drafts],
            axis=1)
        need = max(self._pidx[s] for s in self._inflight) + k
        nb = pow2_bucket(-(-need // self._kv_bs), 1, self._mb)
        t0 = time.perf_counter()
        links = ([f.req.request_id for f in self._inflight.values()]
                 if tracing.tracing_enabled() else ())
        with span("serving.spec_verify", slots=len(self._inflight),
                  k=k, links=links):
            out, self._pool_kv = self._paged_verify_fn(
                self.variables, self._pool_kv,
                jnp.asarray(self._table), jnp.asarray(self._pidx),
                jnp.asarray(toks), k, nb,
            )
            fetch = start_fetch(out, path="decode")
            jax.block_until_ready(out)
            # sparkdl-lint: disable=blocking-in-hot-loop -- block_until_ready above completed the dispatch; only the already-enqueued D2H copy remains
            out = np.asarray(fetch.result())
        wall = time.perf_counter() - t0
        record_dispatch("decode", k, wall)
        # the deadline bound's per-token estimate: a width-k verify is
        # ~ONE model pass (weight-bound regime), so record it as one
        # step — recording k would shrink program_s k-fold and let
        # _bounded_tokens fuse plain chains far past a deadline's real
        # headroom. Slightly overestimating per-token cost (L=k costs
        # ~1.2x L=1) only makes the deadline caps more conservative.
        self._chain_policy.record(wall, 1)
        self.metrics.record_batch(len(self._inflight), self.n_slots)
        self._spec_dispatches += 1
        accepted = 0
        for slot in list(self._inflight):
            flight = self._inflight[slot]
            m = greedy_accept(drafts[slot], out[slot, :k - 1])
            accepted += min(m, real_len.get(slot, 0))
            # outputs [:m+1] are real greedy tokens (m accepted drafts
            # + the bonus/correction); append with the SAME per-token
            # retire semantics as the chained path — eos or budget
            # mid-span drops the rest and frees the slot now
            for j in range(m + 1):
                flight.produced.append(int(out[slot, j]))
                self._last_tok[slot] = out[slot, j]
                self._pidx[slot] += 1
                self._spec_tokens += 1
                if self._is_done(flight):
                    self._complete(slot)
                    break
        self._spec_proposed += proposed
        self._spec_accepted += accepted
        self._spec_policy.record(proposed, accepted)
        _M_SPEC_PROPOSED.inc(proposed)
        if accepted:
            _M_SPEC_ACCEPTED.inc(accepted)
        with _SPEC_TOTALS_LOCK:
            _SPEC_TOTALS["proposed"] += proposed
            _SPEC_TOTALS["accepted"] += accepted
            _M_SPEC_RATE.set(
                _SPEC_TOTALS["accepted"] / _SPEC_TOTALS["proposed"])
        return True

    def _decode_step(self) -> None:
        import jax.numpy as jnp

        if (self.spec_k is not None and self.kv_layout == "paged"
                and self._spec_step()):
            return
        k = self._decode_chain_len(time.monotonic())
        t0 = time.perf_counter()
        # decode ticks are batch-level: their spans link every rider's
        # request id so each request's trace pulls in its decode steps
        links = ([f.req.request_id for f in self._inflight.values()]
                 if tracing.tracing_enabled() else ())
        with span("serving.decode_step", slots=len(self._inflight),
                  chain=k, links=links):
            # Async token readback (runtime/completion.py): the D2H copy
            # of the token ids is enqueued the moment the decode dispatch
            # is — it rides behind the compute instead of waiting for the
            # host to come back with a blocking np.asarray after the
            # program retires (one relay RTT saved per decode dispatch).
            # block_until_ready splits compute from collection so
            # sparkdl_fetch_wait_seconds{path="decode"} meters ONLY the
            # residual copy wait, not the decode program itself.
            import jax

            if self.kv_layout == "paged":
                from sparkdl_tpu.runtime.batching import pow2_bucket

                # static gather width: blocks covering the deepest live
                # row through this whole chain (idx advances k), bucketed
                # to a power of two for compile reuse, capped at the
                # table width
                need = max((self._pidx[s] for s in self._inflight),
                           default=0) + k
                nb = pow2_bucket(-(-need // self._kv_bs), 1, self._mb)
                toks, self._pool_kv = self._paged_step_fn(
                    self.variables, self._pool_kv,
                    jnp.asarray(self._table), jnp.asarray(self._pidx),
                    jnp.asarray(self._last_tok), k, nb,
                )
                fetch = start_fetch(toks, path="decode")
                jax.block_until_ready(toks)
                # sparkdl-lint: disable=blocking-in-hot-loop -- block_until_ready above completed the dispatch; only the already-enqueued D2H copy remains
                toks = np.asarray(fetch.result())
            elif k == 1:
                tok, self._cache = self._step_fn(
                    self.variables, self._cache,
                    jnp.asarray(self._last_tok), jnp.asarray(self._start),
                )
                fetch = start_fetch(tok, path="decode")
                jax.block_until_ready(tok)
                # sparkdl-lint: disable=blocking-in-hot-loop -- block_until_ready above completed the dispatch; only the already-enqueued D2H copy remains
                toks = np.asarray(fetch.result())[None]
            else:
                toks, self._cache = self._step_chain_fn(
                    self.variables, self._cache,
                    jnp.asarray(self._last_tok), k,
                    jnp.asarray(self._start),
                )
                fetch = start_fetch(toks, path="decode")
                jax.block_until_ready(toks)
                # sparkdl-lint: disable=blocking-in-hot-loop -- block_until_ready above completed the dispatch; only the already-enqueued D2H copy remains
                toks = np.asarray(fetch.result())
        wall = time.perf_counter() - t0
        record_dispatch("decode", k, wall)
        self._chain_policy.record(wall, k)
        self.metrics.record_batch(len(self._inflight), self.n_slots)
        paged = self.kv_layout == "paged"
        for j in range(k):
            live = [s for s in self._inflight]
            if not live:
                break
            for slot in live:
                flight = self._inflight[slot]
                flight.produced.append(int(toks[j, slot]))
                self._last_tok[slot] = toks[j, slot]
                if paged:
                    # one column written per decoded token: keep the
                    # host block-table cursor in lockstep
                    self._pidx[slot] += 1
                if self._is_done(flight):
                    # eos (or budget) mid-chain: any later tokens the
                    # chain decoded for this row are simply dropped —
                    # rows are independent, so they influenced nobody
                    self._complete(slot)

    def _is_done(self, flight: _InFlight) -> bool:
        return (len(flight.produced) >= flight.max_new
                or (self.eos_id is not None
                    and flight.produced[-1] == self.eos_id))

    def _record_request_span(self, req: Request, now: float, *,
                             ok: bool, tokens: int,
                             error: "Exception | None" = None) -> None:
        if tracing.tracing_enabled():
            tracing.record_span(
                "serving.request", req.enqueued, now,
                parent=req.trace_ctx, request_id=req.request_id,
                ok=ok, tokens=tokens,
                **({"error": type(error).__name__} if error else {}),
            )

    def _register_session(self, slot: int, flight: _InFlight) -> None:
        """Index the finished turn's whole sequence — prompt plus
        produced tokens minus the last (columns ``[0, pidx)`` hold
        exactly the KV of ``prompt + produced[:-1]``, the _pidx
        invariant) — so the session's NEXT turn, whose prompt embeds
        this turn verbatim, parks and resumes instead of
        re-prefilling. Tiered engines only: without a park tier the
        extra registrations would just bloat the LRU."""
        seq = (tuple(int(t) for t in flight.prompt)
               + tuple(int(t) for t in flight.produced[:-1]))
        if not seq:
            return
        nb = -(-len(seq) // self._kv_bs)
        row = self._table[slot]
        self._prefix.register(seq, [int(b) for b in row[:nb]])

    def _complete(self, slot: int) -> None:
        flight = self._inflight.pop(slot)
        if self._kv_tiers is not None:
            self._register_session(slot, flight)
        self._release_slot(slot, flight.blocks)
        now = time.monotonic()
        self._record_request_span(
            flight.req, now, ok=True, tokens=len(flight.produced))
        flight.req.future.set_result(
            np.asarray(flight.produced, np.int32)
        )
        self.metrics.record_request(now - flight.req.enqueued, ok=True)
        reg = self.queue.tenants
        if reg is not None:
            reg.note_outcome(flight.req.tenant,
                             now - flight.req.enqueued, ok=True)

    def _fail_request(self, req: Request, exc: Exception, *,
                      tokens: int) -> None:
        """The one failure sequence every retire-with-error path shares:
        terminal span, Future exception, shed-load counter, latency
        metric. Skips Futures already resolved elsewhere."""
        if req.future.done():
            return
        now = time.monotonic()
        self._record_request_span(
            req, now, ok=False, tokens=tokens, error=exc)
        req.future.set_exception(exc)
        record_request_failure(exc, request_id=req.request_id)
        self.metrics.record_request(now - req.enqueued, ok=False)
        reg = self.queue.tenants
        if reg is not None:
            reg.note_outcome(req.tenant, now - req.enqueued, ok=False)

    def _expire_inflight(self, now: float) -> None:
        for slot in list(self._inflight):
            flight = self._inflight[slot]
            if flight.req.expired(now):
                self._inflight.pop(slot)
                self._release_slot(slot, flight.blocks)
                self._fail_request(
                    flight.req,
                    DeadlineExceededError(
                        "deadline exceeded mid-decode "
                        f"({len(flight.produced)}/{flight.max_new} "
                        "tokens)"),
                    tokens=len(flight.produced))
        for slot in list(self._prefilling):
            st = self._prefilling[slot]
            if st.req.expired(now):
                self._prefilling.pop(slot)
                self._release_slot(slot, st.all_blocks())
                self._release_sp_staging(st)
                self._fail_request(
                    st.req,
                    DeadlineExceededError(
                        "deadline exceeded mid-prefill "
                        f"({st.pos}/{len(st.prompt)} prompt tokens)"),
                    tokens=0)

    def _fail_inflight(self, exc: Exception) -> None:
        for slot in list(self._inflight):
            flight = self._inflight.pop(slot)
            self._release_slot(slot, flight.blocks)
            self._fail_request(flight.req, exc,
                               tokens=len(flight.produced))
        for slot in list(self._prefilling):
            st = self._prefilling.pop(slot)
            self._release_slot(slot, st.all_blocks())
            self._release_sp_staging(st)
            self._fail_request(st.req, exc, tokens=0)

    # -- introspection -------------------------------------------------------
    @property
    def active_slots(self) -> int:
        return len(self._inflight)

    def trace(self, request_id: int) -> "list[dict]":
        """Every finished span of one request's trace (queue wait,
        prefill, its decode-step dispatches via links, the terminal
        ``serving.request``). Empty with tracing off."""
        return tracing.spans_for_trace(request_id)

    def inflight_request_ids(self) -> "list[int]":
        """Ids of queued + prefilling + decoding requests (postmortem
        input). Best-effort: read without the engine lock."""
        out = self.queue.pending_request_ids()
        try:
            out.extend(f.req.request_id
                       for f in list(self._inflight.values()))
            out.extend(s.req.request_id
                       for s in list(self._prefilling.values()))
        except RuntimeError:  # pragma: no cover - mutation race
            pass
        return out

    def _kv_snapshot(self) -> "dict[str, Any] | None":
        from sparkdl_tpu.serving.kv_blocks import (
            kv_bytes_per_token,
            kv_capacity_ratio,
        )

        if self.kv_layout != "paged":
            return None
        return {
            "block_size": self._kv_bs,
            "blocks_total": self._pool.n_blocks,
            "blocks_used": self._pool.used_count,
            "blocks_used_peak": self._pool.used_peak,
            "blocks_spare": self._pool.spare_count,
            "blocks_cached": self._prefix.cached_blocks,
            "prefix_hits": self._prefix.hit_tokens,
            "prefix_misses": self._prefix.miss_tokens,
            "prefix_evictions": self._prefix.evictions,
            "prefill_chunk": self.prefill_chunk,
            "prefill_chunks": self._prefill_chunks,
            "deferrals_total": self._deferrals,
            # the MAX of decode + staging streaks: /healthz reads this
            # as degraded, and a staging-only stall must degrade too
            "exhausted_streak": max(
                self._pool.deferral_streak,
                self._sp_pool.deferral_streak if self.sp > 1 else 0),
            "dtype": self.kv_dtype,
            "bytes_per_token": kv_bytes_per_token(
                self.config, self.kv_dtype),
            "capacity_ratio_vs_fp32": round(kv_capacity_ratio(
                self.config, self.kv_dtype), 4),
            **({"sp": {
                "axis": self.sp,
                "staging_blocks_total": self._sp_pool.n_blocks,
                "staging_blocks_used": self._sp_pool.used_count,
                "staging_streak": self._sp_pool.deferral_streak,
                "shard_used": self._sp_pool.shard_used_counts(),
                "handoffs": self._sp_handoffs,
            }} if self.sp > 1 else {}),
            # host/disk tier occupancy rides the same snapshot into
            # the flight recorder's pool-pressure context and healthz
            **({"tiers": {
                **(self._prefix.tier_stats() or {}),
                "park_fallbacks": self._park_fallbacks,
                "unpark_reserved": self._pool.unpark_reserved,
            }} if self._kv_tiers is not None else {}),
        }

    def _spec_snapshot(self) -> "dict[str, Any] | None":
        if self.spec_k is None:
            return None
        return {
            "spec_k": self.spec_k,
            "dispatches": self._spec_dispatches,
            "fallbacks": self._spec_fallbacks,
            "proposed": self._spec_proposed,
            "accepted": self._spec_accepted,
            "acceptance_rate": (
                round(self._spec_accepted / self._spec_proposed, 4)
                if self._spec_proposed else None),
            "tokens": self._spec_tokens,
            "tokens_per_dispatch": (
                round(self._spec_tokens / self._spec_dispatches, 4)
                if self._spec_dispatches else None),
        }

    def _flight_context(self) -> dict:
        out = self.metrics.snapshot(self.queue)
        out["active_slots"] = self.active_slots
        out["prefilling_slots"] = len(self._prefilling)
        out["inflight_request_ids"] = self.inflight_request_ids()
        kv = self._kv_snapshot()
        if kv is not None:
            # healthz_report aggregates this shape: a nonzero
            # exhaustion streak reads as degraded (self-recovering)
            out["kv_pool"] = kv
        spec = self._spec_snapshot()
        if spec is not None:
            out["spec"] = spec
        ctrl = tenancy.process_overload()
        if ctrl is not None:
            out["overload"] = ctrl.snapshot()
        reg = self.queue.tenants
        if reg is not None:
            out["tenants"] = reg.snapshot()
        return out

    def kv_autoscale_binding(self) -> "tuple[Any, Any]":
        """``(pool, lock)`` for the elastic autoscaler's KV actuator
        (ISSUE 15): the block pool whose serving/spare split the
        controller resizes, plus the engine lock that guards every
        pool mutation — ``AutoScaler(kv_pool=pool, kv_lock=lock)``
        then grows/shrinks without racing admission."""
        if self.kv_layout != "paged":
            raise RuntimeError(
                "KV autoscaling needs kv_layout='paged' (the dense "
                "layout has no block pool to resize)")
        return self._pool, self._lock

    def capacity(self) -> "dict[str, Any]":
        """The one structure a router's weighting reads (ISSUE 14):
        identity + room, instead of poking queue, pool, and slot state
        separately. Best-effort reads (no engine lock): routing weights
        tolerate a tick of staleness."""
        paged = self.kv_layout == "paged"
        # parkable pressure split (ROADMAP item 1): cold = refcount-0
        # cached blocks that COULD page out on demand, parked = blocks
        # already in the host/disk tiers. A router that reads only
        # kv_blocks_free scores a host full when its pressure is
        # actually idle sessions — the headroom policy folds these in.
        cold = parked = sessions = None
        if paged:
            try:
                cold = self._prefix.cold_blocks()
            except RuntimeError:
                cold = None  # racing registration: stale next refresh
            if self._kv_tiers is not None:
                s = self._kv_tiers.stats()
                parked = s["host_blocks"] + s["disk_blocks"]
                try:
                    sessions = self._prefix.parked_sessions()
                except RuntimeError:
                    sessions = None
        return {
            "host_id": self.host_id,
            "replica_count": 1,
            "n_slots": self.n_slots,
            "free_slots": (self.n_slots - len(self._inflight)
                           - len(self._prefilling)),
            "kv_blocks_free": self._pool.free_count if paged else None,
            "kv_blocks_total": self._pool.n_blocks if paged else None,
            "kv_blocks_cold": cold,
            "kv_parked_blocks": parked,
            "kv_parked_sessions": sessions,
            "queue_depth": self.queue.depth,
            "max_queue_depth": self.queue.max_depth,
            "draining": self.queue.closed,
            # brownout level (ISSUE 20): a router discounts a
            # browned-out host's headroom so the fleet routes around
            # local overload while the ladder sheds it
            "overload_level": tenancy.overload_level(),
        }

    def snapshot(self) -> dict[str, Any]:
        out = self.metrics.snapshot(self.queue)
        out["host_id"] = self.host_id
        out["capacity"] = self.capacity()
        out["active_slots"] = self.active_slots
        out["n_slots"] = self.n_slots
        out["kv_layout"] = self.kv_layout
        out["prefill_seconds"] = self._prefill_seconds
        out["kv"] = self._kv_snapshot()
        out["spec"] = self._spec_snapshot()
        out["slo"] = (self.slo_tracker.sample()
                      if self.slo_tracker is not None else None)
        return out

    def __enter__(self) -> "ContinuousGPTEngine":
        return self

    def __exit__(self, *exc) -> None:
        self.close(drain=exc == (None, None, None))
