"""Continuous batching for GPT decode: rows join and leave mid-stream.

The lockstep ``generate`` path (models/gpt.py) starts a batch together
and ends it together, so one long row holds every slot hostage and new
arrivals wait for the whole batch to finish — fatal for online serving.
This engine keeps ONE persistent decode batch of ``n_slots`` rows over a
per-slot KV cache (``init_cache(per_slot=True)``: ``idx`` per row):

- a finished row frees its slot immediately;
- a newly admitted prompt is prefilled ALONE (batch-1, bucketed prompt
  length, the jit-cached left-padded ragged path) and its K/V row is
  scattered into the free slot — the in-flight neighbors never notice;
- every engine tick advances all live rows one token in a single jitted
  step whose per-row causal mask lets each row decode at its own depth.

Token identity: greedy tokens of every request are IDENTICAL to its
unbatched ``generate`` decode (tests/serving/test_continuous_gpt.py) —
batching is a scheduling decision, never a quality decision.

Decode is greedy (temperature 0), the deterministic serving default;
sampled decode stays on the lockstep ``DeepTextGenerator`` path.
"""

from __future__ import annotations

import dataclasses
import functools
import threading
import time
from concurrent.futures import Future
from typing import Any, Optional

import numpy as np

from sparkdl_tpu.observability import slo as slo_mod
from sparkdl_tpu.observability import tracing
from sparkdl_tpu.observability.tracing import span
from sparkdl_tpu.runtime.completion import start_fetch
from sparkdl_tpu.runtime.dispatch import ChainPolicy, record_dispatch
from sparkdl_tpu.serving.metrics import ServingMetrics
from sparkdl_tpu.serving.queue import (
    DeadlineExceededError,
    EngineClosedError,
    Request,
    RequestQueue,
    record_request_failure,
)


@dataclasses.dataclass
class GenRequest:
    """One generation request: prompt token ids + token budget."""

    prompt: np.ndarray
    max_new_tokens: int


@dataclasses.dataclass
class _InFlight:
    """Host-side state of one occupied slot (the left-pad count lives in
    the engine's ``_start`` array the decode step consumes)."""

    req: Request
    produced: list[int]
    max_new: int


class ContinuousGPTEngine:
    """Async continuous-batching GPT server.

    ``submit(prompt_ids, max_new_tokens)`` returns a Future of the
    generated ids (prompt not included). Admission control is two-layer:
    queue depth (QueueFullError) and cache capacity — a request whose
    bucketed prompt + budget cannot fit ``max_len`` columns is rejected
    at submit, loudly, because its cache writes would silently drop.

    ``auto_start=False`` exposes :meth:`tick` for deterministic
    single-step tests; the default runs the loop on a daemon thread.

    ``chain_tokens`` fuses up to k decode steps into ONE device dispatch
    (``lax.scan`` over the donated cache — runtime/dispatch.py): a
    decode step is tiny next to the per-dispatch gap, so the unchained
    loop pays a full dispatch *per generated token*. Chaining trades
    admission/retirement granularity (checks run every k tokens, not
    every token) for k-fold dispatch amortization; k is re-bounded every
    tick by the smallest remaining token budget in flight (the earliest
    possible retirement — nothing is decoded past it) and by the
    tightest in-flight deadline over the measured per-token time, so
    p99 latency does not regress. Greedy tokens are identical at any k.
    None = auto-calibrate from the dispatch gap; 1 (default) = one
    token per dispatch, the exact pre-chaining tick semantics.
    """

    def __init__(self, config, variables, *, n_slots: int = 8,
                 max_len: int = 512, max_queue_depth: int = 256,
                 eos_id: Optional[int] = None,
                 idle_wait_s: float = 0.005,
                 chain_tokens: "int | None" = 1,
                 metrics: ServingMetrics | None = None,
                 slo: "slo_mod.SLO | None" = None,
                 auto_start: bool = True):
        import jax
        import jax.numpy as jnp
        from jax import lax

        from sparkdl_tpu.models.gpt import (
            GPTLMHeadModel,
            init_cache,
        )
        from sparkdl_tpu.runtime.batching import default_buckets

        if n_slots < 1:
            raise ValueError(f"n_slots must be >= 1, got {n_slots}")
        if chain_tokens is not None and chain_tokens < 1:
            raise ValueError(
                f"chain_tokens must be >= 1, got {chain_tokens}"
            )
        if (config.positions == "learned"
                and max_len > config.max_seq_len):
            raise ValueError(
                f"max_len {max_len} exceeds the learned position table "
                f"(max_seq_len={config.max_seq_len})"
            )
        self.config = config
        self.variables = variables
        self.n_slots = n_slots
        self.max_len = max_len
        self.eos_id = eos_id
        self.idle_wait_s = idle_wait_s
        self.chain_tokens = chain_tokens
        self._chain_policy = ChainPolicy(
            max_chain=chain_tokens if chain_tokens is not None else 32
        )
        if chain_tokens is None:
            # auto mode reads the gap per tick: calibrate once here,
            # outside the engine lock, never inside the decode loop
            self._chain_policy.gap()
        self.queue = RequestQueue(max_depth=max_queue_depth)
        self.metrics = metrics if metrics is not None else ServingMetrics()
        self._model = GPTLMHeadModel(config)
        self._len_buckets = default_buckets(max_len, min_bucket=8)
        self._inflight: dict[int, _InFlight] = {}
        self._cache = init_cache(config, n_slots, max_len, per_slot=True)
        self._start = np.zeros((n_slots,), np.int32)
        self._last_tok = np.zeros((n_slots,), np.int32)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

        model = self._model

        @jax.jit
        def _prefill(variables, ids, mask):
            # batch-1 left-padded prefill in a fresh scalar-idx cache of
            # the SHARED buffer width, so columns line up at scatter time.
            # jit's shape cache gives one compile per prompt-length bucket.
            lp = ids.shape[1]
            cache = init_cache(config, 1, max_len)
            positions = jnp.clip(jnp.cumsum(mask, axis=1) - 1, 0)
            key_valid = jnp.concatenate(
                [mask.astype(bool),
                 jnp.ones((1, max_len - lp), bool)], axis=1,
            )
            logits, cache = model.apply(
                variables, ids, cache=cache, positions=positions,
                attention_mask=key_valid,
            )
            return jnp.argmax(logits[:, -1], axis=-1), cache

        # donate the cache through scatter and step: the engine always
        # discards the old version, and without donation every token
        # would materialize a second full [layers, S, max_len, H, D]
        # buffer (2x HBM peak + a copy per token at serving sizes)
        @functools.partial(jax.jit, donate_argnums=(0,))
        def _scatter(cache, row, slot):
            # install a prefilled row into slot (traced index: one compile)
            return {
                "k": jax.lax.dynamic_update_slice_in_dim(
                    cache["k"], row["k"], slot, axis=1),
                "v": jax.lax.dynamic_update_slice_in_dim(
                    cache["v"], row["v"], slot, axis=1),
                "idx": cache["idx"].at[slot].set(
                    row["idx"].astype(jnp.int32)),
            }

        @functools.partial(jax.jit, donate_argnums=(1,))
        def _step(variables, cache, tok, start):
            # one token for every slot; the per-slot cache gives each row
            # its own causal depth, `start` masks its left-pad columns,
            # and RoPE/learned positions count real tokens only
            positions = (cache["idx"] - start)[:, None]
            key_valid = jnp.arange(max_len)[None, :] >= start[:, None]
            logits, cache = model.apply(
                variables, tok[:, None], cache=cache, positions=positions,
                attention_mask=key_valid,
            )
            return jnp.argmax(logits[:, -1], axis=-1), cache

        @functools.partial(jax.jit, donate_argnums=(1,),
                           static_argnums=(3,))
        def _step_chain(variables, cache, tok, k, start):
            # k tokens per dispatch: scan the single-step body carrying
            # (cache, tok) — each step's argmax feeds the next, exactly
            # the unchained sequence, amortizing the dispatch gap k-fold.
            # The carried cache IS the iteration dependence (no CSE
            # collapse possible) and rides the donated input buffer.
            def body(carry, _):
                cache, tok = carry
                positions = (cache["idx"] - start)[:, None]
                key_valid = (jnp.arange(max_len)[None, :]
                             >= start[:, None])
                logits, cache = model.apply(
                    variables, tok[:, None], cache=cache,
                    positions=positions, attention_mask=key_valid,
                )
                tok = jnp.argmax(logits[:, -1], axis=-1)
                return (cache, tok), tok

            (cache, _), toks = lax.scan(
                body, (cache, tok), None, length=k
            )
            return toks, cache

        self._prefill_fn = _prefill
        self._scatter_fn = _scatter
        self._step_fn = _step
        self._step_chain_fn = _step_chain
        # process-wide registrations go LAST: a constructor failure above
        # (bad config, cache init OOM) must not leak a tracker/provider
        # bound to a half-built engine
        from sparkdl_tpu.serving.metrics import EngineObservability

        self._obs = EngineObservability(
            "continuous", self._flight_context, slo=slo, n_slots=n_slots)
        self.slo_tracker = self._obs.tracker
        if auto_start:
            self.start()

    # -- submission ----------------------------------------------------------
    def submit(self, prompt_ids, max_new_tokens: int, *,
               timeout_s: float | None = None) -> Future:
        """Admit one prompt; Future resolves to the generated ids
        (np.int32 array, ``<= max_new_tokens`` long — shorter on eos)."""
        from sparkdl_tpu.runtime.batching import pick_bucket

        prompt = np.asarray(prompt_ids, np.int32)
        if prompt.ndim != 1 or prompt.size == 0:
            raise ValueError(
                f"prompt must be a non-empty 1-D id array, got shape "
                f"{prompt.shape}"
            )
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}"
            )
        lp = pick_bucket(len(prompt), self._len_buckets)
        if lp + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt bucket {lp} + max_new_tokens {max_new_tokens} "
                f"exceeds cache max_len {self.max_len}: raise max_len or "
                "shorten the request"
            )
        return self.queue.submit(
            GenRequest(prompt, max_new_tokens), timeout_s=timeout_s
        )

    # -- engine loop ---------------------------------------------------------
    def start(self) -> "ContinuousGPTEngine":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="sparkdl-continuous-gpt", daemon=True
            )
            self._thread.start()
        return self

    def close(self, *, drain: bool = True,
              timeout_s: float | None = 30.0) -> None:
        """Stop. ``drain=True`` finishes every admitted request (queued
        and in-flight) first; ``drain=False`` fails them now."""
        self.queue.close()
        if not drain:
            self.queue.fail_pending()
            self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout_s)
        elif drain:  # manual-tick mode: drain inline
            while self.queue.depth > 0 or self._inflight:
                self.tick()
        self._stop.set()
        # join timeout or a crashed loop may leave requests queued: no
        # Future may ever be left unresolved
        self.queue.fail_pending()
        with self._lock:
            self._fail_inflight(EngineClosedError("engine shut down"))
        self._obs.close(drain=drain)

    def _loop(self) -> None:
        try:
            while not self._stop.is_set():
                did_work = self.tick()
                if self.queue.closed and not did_work:
                    with self._lock:
                        if self.queue.depth == 0 and not self._inflight:
                            return  # graceful drain complete
            # non-graceful: surviving inflight failed by close()
        except BaseException as e:
            # a crashed loop (device OOM, XLA error) must not strand
            # callers blocked on their Futures
            exc = (e if isinstance(e, Exception)
                   else EngineClosedError(f"engine loop died: {e!r}"))
            self.queue.close()
            self.queue.fail_pending(exc)
            with self._lock:
                self._fail_inflight(exc)
            raise

    # -- one scheduling quantum ---------------------------------------------
    def tick(self) -> bool:
        """Admit into free slots, advance every live row one token,
        retire finished rows. Returns True if any work happened (False =
        idle tick). Thread-safe; the background loop is just
        ``while True: tick()``."""
        with self._lock:
            now = time.monotonic()
            self._expire_inflight(now)
            free = [s for s in range(self.n_slots)
                    if s not in self._inflight]
            if free:
                wait = 0.0 if self._inflight else self.idle_wait_s
                for req in self.queue.take(len(free), wait):
                    slot = free.pop(0)
                    try:
                        self._admit(slot, req)
                    except Exception as e:
                        # take() already moved this Future to RUNNING, so
                        # nobody else can resolve it: a failed admission
                        # (prefill OOM, compile error) is THIS request's
                        # error, never the engine's — the slot stays free
                        # and the loop keeps serving
                        free.insert(0, slot)
                        if not req.future.done():
                            self._record_request_span(
                                req, time.monotonic(), ok=False,
                                tokens=0, error=e)
                            req.future.set_exception(e)
                            record_request_failure(
                                e, request_id=req.request_id)
                            self.metrics.record_request(
                                now - req.enqueued, ok=False
                            )
            else:
                self.queue.sweep_expired()  # deadlines don't wait for slots
            if not self._inflight:
                return False
            self._decode_step()
            return True

    def _admit(self, slot: int, req: Request) -> None:
        import jax.numpy as jnp

        from sparkdl_tpu.runtime.batching import pick_bucket

        gen: GenRequest = req.payload
        lp = pick_bucket(len(gen.prompt), self._len_buckets)
        with span("serving.prefill", parent=req.trace_ctx,
                  prompt_len=len(gen.prompt), bucket=lp, slot=slot,
                  request_id=req.request_id):
            ids = np.zeros((1, lp), np.int32)
            mask = np.zeros((1, lp), np.int32)
            ids[0, lp - len(gen.prompt):] = gen.prompt
            mask[0, lp - len(gen.prompt):] = 1
            tok, row = self._prefill_fn(
                self.variables, jnp.asarray(ids), jnp.asarray(mask)
            )
            self._cache = self._scatter_fn(
                self._cache, row, jnp.asarray(slot, jnp.int32)
            )
            first = int(tok[0])
        self._start[slot] = lp - len(gen.prompt)
        self._last_tok[slot] = first
        flight = _InFlight(req, [first], gen.max_new_tokens)
        self._inflight[slot] = flight
        if self._is_done(flight):  # max_new_tokens=1, or instant eos
            self._complete(slot)

    def _decode_chain_len(self, now: float) -> int:
        """Tokens to fuse into the next decode dispatch.

        Bounded by (a) the configured/auto cap, (b) the smallest
        remaining token budget in flight — the earliest possible
        retirement, so no slot is held past its scheduled exit and no
        decoded token is wasted on budget grounds — and (c) the tightest
        in-flight deadline over the measured per-token time (2x safety),
        so a request never expires inside a chain it could have survived.
        Rounded down to a power of two: at most log2(cap) compiled chain
        programs ever exist.
        """
        cap = (self.chain_tokens if self.chain_tokens is not None
               else self._chain_policy.chain_len())
        cap = min(cap, *(
            f.max_new - len(f.produced) for f in self._inflight.values()
        ))
        tok_s = self._chain_policy.program_s
        if tok_s:
            for f in self._inflight.values():
                if f.req.deadline is not None:
                    headroom = (f.req.deadline - now) / (2.0 * tok_s)
                    cap = min(cap, int(headroom))
        elif any(f.req.deadline is not None
                 for f in self._inflight.values()):
            # no per-token estimate yet and a deadline is in flight: the
            # first dispatch doubles as the measurement probe at k=1 so
            # a request can never expire inside an unmeasured chain
            return 1
        if cap <= 1:
            return 1
        return 1 << (cap.bit_length() - 1)

    def _decode_step(self) -> None:
        import jax.numpy as jnp

        k = self._decode_chain_len(time.monotonic())
        t0 = time.perf_counter()
        # decode ticks are batch-level: their spans link every rider's
        # request id so each request's trace pulls in its decode steps
        links = ([f.req.request_id for f in self._inflight.values()]
                 if tracing.tracing_enabled() else ())
        with span("serving.decode_step", slots=len(self._inflight),
                  chain=k, links=links):
            # Async token readback (runtime/completion.py): the D2H copy
            # of the token ids is enqueued the moment the decode dispatch
            # is — it rides behind the compute instead of waiting for the
            # host to come back with a blocking np.asarray after the
            # program retires (one relay RTT saved per decode dispatch).
            # block_until_ready splits compute from collection so
            # sparkdl_fetch_wait_seconds{path="decode"} meters ONLY the
            # residual copy wait, not the decode program itself.
            import jax

            if k == 1:
                tok, self._cache = self._step_fn(
                    self.variables, self._cache,
                    jnp.asarray(self._last_tok), jnp.asarray(self._start),
                )
                fetch = start_fetch(tok, path="decode")
                jax.block_until_ready(tok)
                toks = np.asarray(fetch.result())[None]
            else:
                toks, self._cache = self._step_chain_fn(
                    self.variables, self._cache,
                    jnp.asarray(self._last_tok), k,
                    jnp.asarray(self._start),
                )
                fetch = start_fetch(toks, path="decode")
                jax.block_until_ready(toks)
                toks = np.asarray(fetch.result())
        wall = time.perf_counter() - t0
        record_dispatch("decode", k, wall)
        self._chain_policy.record(wall, k)
        self.metrics.record_batch(len(self._inflight), self.n_slots)
        for j in range(k):
            live = [s for s in self._inflight]
            if not live:
                break
            for slot in live:
                flight = self._inflight[slot]
                flight.produced.append(int(toks[j, slot]))
                self._last_tok[slot] = toks[j, slot]
                if self._is_done(flight):
                    # eos (or budget) mid-chain: any later tokens the
                    # chain decoded for this row are simply dropped —
                    # rows are independent, so they influenced nobody
                    self._complete(slot)

    def _is_done(self, flight: _InFlight) -> bool:
        return (len(flight.produced) >= flight.max_new
                or (self.eos_id is not None
                    and flight.produced[-1] == self.eos_id))

    def _record_request_span(self, req: Request, now: float, *,
                             ok: bool, tokens: int,
                             error: "Exception | None" = None) -> None:
        if tracing.tracing_enabled():
            tracing.record_span(
                "serving.request", req.enqueued, now,
                parent=req.trace_ctx, request_id=req.request_id,
                ok=ok, tokens=tokens,
                **({"error": type(error).__name__} if error else {}),
            )

    def _complete(self, slot: int) -> None:
        flight = self._inflight.pop(slot)
        now = time.monotonic()
        self._record_request_span(
            flight.req, now, ok=True, tokens=len(flight.produced))
        flight.req.future.set_result(
            np.asarray(flight.produced, np.int32)
        )
        self.metrics.record_request(now - flight.req.enqueued, ok=True)

    def _expire_inflight(self, now: float) -> None:
        for slot in list(self._inflight):
            flight = self._inflight[slot]
            if flight.req.expired(now):
                self._inflight.pop(slot)
                exc = DeadlineExceededError(
                    "deadline exceeded mid-decode "
                    f"({len(flight.produced)}/{flight.max_new} tokens)"
                )
                self._record_request_span(
                    flight.req, now, ok=False,
                    tokens=len(flight.produced), error=exc)
                flight.req.future.set_exception(exc)
                record_request_failure(
                    exc, request_id=flight.req.request_id)
                self.metrics.record_request(
                    now - flight.req.enqueued, ok=False
                )

    def _fail_inflight(self, exc: Exception) -> None:
        for slot in list(self._inflight):
            flight = self._inflight.pop(slot)
            if not flight.req.future.done():
                now = time.monotonic()
                self._record_request_span(
                    flight.req, now, ok=False,
                    tokens=len(flight.produced), error=exc)
                flight.req.future.set_exception(exc)
                record_request_failure(
                    exc, request_id=flight.req.request_id)
                self.metrics.record_request(
                    now - flight.req.enqueued, ok=False
                )

    # -- introspection -------------------------------------------------------
    @property
    def active_slots(self) -> int:
        return len(self._inflight)

    def trace(self, request_id: int) -> "list[dict]":
        """Every finished span of one request's trace (queue wait,
        prefill, its decode-step dispatches via links, the terminal
        ``serving.request``). Empty with tracing off."""
        return tracing.spans_for_trace(request_id)

    def inflight_request_ids(self) -> "list[int]":
        """Ids of queued + decoding requests (postmortem input).
        Best-effort: read without the engine lock."""
        out = self.queue.pending_request_ids()
        try:
            out.extend(f.req.request_id
                       for f in list(self._inflight.values()))
        except RuntimeError:  # pragma: no cover - mutation race
            pass
        return out

    def _flight_context(self) -> dict:
        out = self.metrics.snapshot(self.queue)
        out["active_slots"] = self.active_slots
        out["inflight_request_ids"] = self.inflight_request_ids()
        return out

    def snapshot(self) -> dict[str, Any]:
        out = self.metrics.snapshot(self.queue)
        out["active_slots"] = self.active_slots
        out["n_slots"] = self.n_slots
        out["slo"] = (self.slo_tracker.sample()
                      if self.slo_tracker is not None else None)
        return out

    def __enter__(self) -> "ContinuousGPTEngine":
        return self

    def __exit__(self, *exc) -> None:
        self.close(drain=exc == (None, None, None))
