"""Multi-tenant QoS: admission quotas, fair weights, brownout ladder.

The serving stack routes, batches, parks, and autoscales — but until
now every request was anonymous: one hot tenant flooding
``RequestQueue.submit`` starved everyone else, and the only overload
response was a binary ``QueueFullError``. This module is the identity
and policy layer under ROADMAP item 3(a):

* :class:`TenantRegistry` — per-tenant **token buckets** (rate +
  burst, runtime re-configurable) reject over-quota submits with a
  typed :class:`TenantThrottledError` at the door, *before* the
  request consumes queue depth. Unknown tenants (and the ``default``
  tenant nobody configured) are admitted unconditionally — every
  existing single-user call site behaves bitwise as before. The
  registry also owns per-tenant accounting: admitted/shed/failed
  counters, a per-tenant latency histogram, and rolling per-tenant
  SLO compliance/burn reusing the exact
  :meth:`~sparkdl_tpu.observability.slo.SLOTracker._dimension`
  arithmetic, published under the same ``sparkdl_slo_*`` gauges with
  ``slo="tenant:<name>"`` labels.
* **Priority classes** — requests carry an integer ``priority``
  (LOWER is MORE urgent; :data:`PRIORITY_INTERACTIVE` = 0 is the
  default, :data:`PRIORITY_BACKGROUND` = 10 is the offline class the
  :class:`~sparkdl_tpu.disagg.filler.BatchPrefillFiller` rides).
  :class:`~sparkdl_tpu.serving.queue.RequestQueue` serves classes in
  strict priority order and tenants *within* a class by
  deficit-weighted round-robin, so a deep queue from one tenant
  cannot monopolize micro-batch slots.
* :class:`OverloadController` — the process-wide **brownout ladder**.
  Driven by SLO burn + queue depth, it steps through degradation
  levels (shed the background class → shrink spec_k/chain_tokens →
  double-charge quota'd tenants → reject new work) and back down,
  with the same hold-N-consecutive-ticks hysteresis discipline the
  AutoTuner/AutoScaler use, so a noisy signal cannot flap the fleet.
  Levels land in ``/healthz`` (via a flight health fact), in
  flight-recorder events, and in every engine's ``capacity()`` so the
  fabric's routers steer traffic around browned-out hosts.

Everything here is deliberately import-light (observability spine
only): ``queue.py`` imports this module, never the reverse.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Callable, Dict, Optional

from sparkdl_tpu.observability import flight
from sparkdl_tpu.observability.registry import registry
from sparkdl_tpu.observability.slo import SLOTracker

__all__ = [
    "BrownoutShedError",
    "OverloadController",
    "PRIORITY_BACKGROUND",
    "PRIORITY_INTERACTIVE",
    "TenantRegistry",
    "TenantThrottledError",
    "TokenBucket",
    "overload_level",
    "process_overload",
    "set_process_overload",
]

#: The default class every existing call site lands in (lower = more
#: urgent; anything < PRIORITY_BACKGROUND is "interactive-ish").
PRIORITY_INTERACTIVE = 0
#: The lowest class: offline/batch work (BatchPrefillFiller, bulk
#: scoring). First shed by the brownout ladder, last served by the
#: scheduler, preemptible mid-prefill by any higher class.
PRIORITY_BACKGROUND = 10

_M_ADMITTED = registry().counter(
    "sparkdl_tenant_admitted_total",
    "requests admitted through a tenant quota check", labels=("tenant",))
_M_SHED = registry().counter(
    "sparkdl_tenant_shed_total",
    "submits rejected over-quota (TenantThrottledError) or by the "
    "brownout ladder (BrownoutShedError)", labels=("tenant",))
_M_FAILED = registry().counter(
    "sparkdl_tenant_failed_total",
    "accepted requests that resolved with an error, per tenant",
    labels=("tenant",))
_M_LATENCY = registry().histogram(
    "sparkdl_tenant_latency_seconds",
    "request latency (submit to result) per tenant",
    labels=("tenant",))
_M_PREEMPTIONS = registry().counter(
    "sparkdl_tenant_preemptions_total",
    "chunked prefills preempted between chunks by a higher-priority "
    "arrival (victim re-queued at its class head, zero lost)")
_M_OVERLOAD_LEVEL = registry().gauge(
    "sparkdl_overload_level",
    "current brownout ladder level (0=normal, 1=shed background, "
    "2=degrade quality, 3=throttle tenants, 4=reject)")
_M_OVERLOAD_TRANSITIONS = registry().counter(
    "sparkdl_overload_transitions_total",
    "brownout ladder level changes", labels=("direction",))
_M_OVERLOAD_SHED = registry().counter(
    "sparkdl_overload_shed_total",
    "submits rejected by the brownout ladder, by the level that shed "
    "them", labels=("level",))


class TenantThrottledError(RuntimeError):
    """Over-quota submit: the tenant's token bucket is empty. Typed —
    the flooder's overage is shed at the door, distinguishable from
    capacity backpressure (``QueueFullError``) and never a timeout."""

    def __init__(self, tenant: str, msg: "str | None" = None):
        super().__init__(
            msg or f"tenant {tenant!r} is over its admission quota; "
            "retry with backoff")
        self.tenant = tenant


class BrownoutShedError(RuntimeError):
    """The brownout ladder shed this submit (level >= 1 sheds the
    background class, level 4 sheds everything). Admission-time only —
    accepted requests are never failed by a level change."""

    def __init__(self, level: int, msg: str):
        super().__init__(msg)
        self.level = level


class TokenBucket:
    """Classic rate + burst token bucket (not self-locking — the
    owning :class:`TenantRegistry` serializes). ``rate`` is tokens/sec
    refilled continuously, ``burst`` the bucket capacity (also the
    initial fill, so a fresh tenant can burst immediately)."""

    __slots__ = ("rate", "burst", "tokens", "_last")

    def __init__(self, rate: float, burst: float,
                 now: "float | None" = None):
        if rate <= 0:
            raise ValueError(f"rate must be > 0, got {rate}")
        if burst < 1:
            raise ValueError(f"burst must be >= 1, got {burst}")
        self.rate = float(rate)
        self.burst = float(burst)
        self.tokens = float(burst)
        self._last = now if now is not None else time.monotonic()

    def reconfigure(self, rate: "float | None" = None,
                    burst: "float | None" = None) -> None:
        """Runtime re-configuration: new rate applies from now; a
        shrunk burst clamps the current fill (no retroactive debt)."""
        if rate is not None:
            if rate <= 0:
                raise ValueError(f"rate must be > 0, got {rate}")
            self.rate = float(rate)
        if burst is not None:
            if burst < 1:
                raise ValueError(f"burst must be >= 1, got {burst}")
            self.burst = float(burst)
            self.tokens = min(self.tokens, self.burst)

    def try_acquire(self, now: "float | None" = None,
                    cost: float = 1.0) -> bool:
        now = now if now is not None else time.monotonic()
        if now > self._last:
            self.tokens = min(self.burst,
                              self.tokens + self.rate * (now - self._last))
        self._last = max(self._last, now)
        if self.tokens >= cost:
            self.tokens -= cost
            return True
        return False


class _TenantSpec:
    """One tenant's policy + rolling outcome window (registry-locked)."""

    __slots__ = ("name", "bucket", "weight", "priority", "admitted",
                 "shed", "failed", "completed", "outcomes")

    def __init__(self, name: str):
        self.name = name
        self.bucket: "TokenBucket | None" = None
        self.weight = 1.0
        self.priority: "int | None" = None
        self.admitted = 0
        self.shed = 0
        self.failed = 0
        self.completed = 0
        #: rolling (t, latency_s, ok) samples for per-tenant SLO math
        self.outcomes: "collections.deque[tuple]" = collections.deque()


class TenantRegistry:
    """Thread-safe tenant policy map + per-tenant accounting.

    ``configure(tenant, rate=, burst=, weight=, priority=)`` declares
    (or re-declares, at runtime) a tenant's quota and fair-share
    weight; ``admit(tenant)`` is the queue's admission hook — it
    raises :class:`TenantThrottledError` when the tenant's bucket is
    empty and counts every decision. Tenants never configured pass
    freely with weight 1 (the bitwise-compatible default path).

    ``slo`` (threshold seconds + targets) turns on per-tenant rolling
    compliance/burn: ``note_outcome`` feeds a bounded window per
    tenant, and :meth:`slo_report` publishes per-tenant rows under the
    shared ``sparkdl_slo_*`` gauges with ``slo="tenant:<name>"``.
    """

    def __init__(self, *,
                 latency_threshold_s: "float | None" = None,
                 latency_target: float = 0.95,
                 availability_target: float = 0.999,
                 window_s: float = 300.0,
                 clock: Callable[[], float] = time.monotonic):
        self._lock = threading.Lock()
        self._tenants: "Dict[str, _TenantSpec]" = {}
        self.latency_threshold_s = latency_threshold_s
        self.latency_target = latency_target
        self.availability_target = availability_target
        self.window_s = window_s
        self._clock = clock

    def _spec_locked(self, tenant: str) -> _TenantSpec:
        spec = self._tenants.get(tenant)
        if spec is None:
            spec = self._tenants[tenant] = _TenantSpec(tenant)
        return spec

    def configure(self, tenant: str, *,
                  rate: "float | None" = None,
                  burst: "float | None" = None,
                  weight: "float | None" = None,
                  priority: "int | None" = None) -> None:
        """Declare or update one tenant. ``rate``/``burst`` configure
        the bucket (rate alone defaults burst to max(1, rate));
        ``weight`` is the DRR fair share within its class (>= 1);
        ``priority`` pins a default class for the tenant's submits."""
        with self._lock:
            spec = self._spec_locked(tenant)
            if rate is not None:
                if spec.bucket is None:
                    spec.bucket = TokenBucket(
                        rate, burst if burst is not None
                        else max(1.0, rate), now=self._clock())
                else:
                    spec.bucket.reconfigure(rate, burst)
            elif burst is not None:
                if spec.bucket is None:
                    raise ValueError(
                        f"tenant {tenant!r} has no rate yet: configure "
                        "rate= before (or with) burst=")
                spec.bucket.reconfigure(None, burst)
            if weight is not None:
                if weight < 1:
                    raise ValueError(
                        f"weight must be >= 1, got {weight}")
                spec.weight = float(weight)
            if priority is not None:
                spec.priority = int(priority)

    def weight(self, tenant: str) -> float:
        with self._lock:
            spec = self._tenants.get(tenant)
            return spec.weight if spec is not None else 1.0

    def default_priority(self, tenant: str) -> "int | None":
        with self._lock:
            spec = self._tenants.get(tenant)
            return spec.priority if spec is not None else None

    def admit(self, tenant: str, now: "float | None" = None,
              cost: float = 1.0) -> None:
        """The admission hook: consume one bucket token (``cost`` > 1
        under brownout level 3) or raise :class:`TenantThrottledError`.
        Unconfigured tenants always pass. Counts both outcomes."""
        with self._lock:
            spec = self._spec_locked(tenant)
            if spec.bucket is not None and not spec.bucket.try_acquire(
                    now if now is not None else self._clock(), cost):
                spec.shed += 1
                _M_SHED.inc(tenant=tenant)
                raise TenantThrottledError(tenant)
            spec.admitted += 1
        _M_ADMITTED.inc(tenant=tenant)

    def count_shed(self, tenant: str) -> None:
        """Record a brownout shed against ``tenant`` (the ladder, not
        the bucket, made the call — same counter, same dashboards)."""
        with self._lock:
            self._spec_locked(tenant).shed += 1
        _M_SHED.inc(tenant=tenant)

    def note_outcome(self, tenant: str, latency_s: float, *,
                     ok: bool) -> None:
        """One finished request's outcome: per-tenant latency histogram,
        failure counter, and the rolling SLO window."""
        _M_LATENCY.observe(latency_s, tenant=tenant)
        if not ok:
            _M_FAILED.inc(tenant=tenant)
        now = self._clock()
        with self._lock:
            spec = self._spec_locked(tenant)
            if ok:
                spec.completed += 1
            else:
                spec.failed += 1
            spec.outcomes.append((now, latency_s, ok))
            horizon = now - self.window_s
            while spec.outcomes and spec.outcomes[0][0] <= horizon:
                spec.outcomes.popleft()

    def slo_report(self) -> "Dict[str, dict]":
        """Per-tenant rolling compliance/burn (the same `_dimension`
        arithmetic the engine-level SLOTracker publishes), pushed to
        the shared ``sparkdl_slo_*`` gauges as ``slo="tenant:<name>"``
        rows. Keyed by tenant name."""
        reg = registry()
        objective = reg.gauge(
            "sparkdl_slo_objective",
            "declared objective (target fraction) per SLO dimension",
            labels=("slo", "dimension"))
        compliance_g = reg.gauge(
            "sparkdl_slo_compliance",
            "rolling-window compliance fraction per SLO dimension",
            labels=("slo", "dimension"))
        burn_g = reg.gauge(
            "sparkdl_slo_burn_rate",
            "error-budget burn rate (error rate / budget; 1.0 = "
            "sustainable pace)",
            labels=("slo", "dimension"))
        now = self._clock()
        horizon = now - self.window_s
        out: "Dict[str, dict]" = {}
        with self._lock:
            for name, spec in self._tenants.items():
                window = [o for o in spec.outcomes if o[0] > horizon]
                total = len(window)
                ok_n = sum(1 for _, _, ok in window if ok)
                row: "dict[str, Any]" = {
                    "tenant": name,
                    "admitted": spec.admitted,
                    "shed": spec.shed,
                    "completed": spec.completed,
                    "failed": spec.failed,
                }
                labels = {"slo": f"tenant:{name}"}
                if self.latency_threshold_s is not None:
                    good = sum(
                        1 for _, lat, _ in window
                        if lat <= self.latency_threshold_s)
                    dim = SLOTracker._dimension(
                        good, total, self.latency_target)
                    dim["threshold_s"] = self.latency_threshold_s
                    row["latency"] = dim
                    objective.set(dim["target"], dimension="latency",
                                  **labels)
                    compliance_g.set(
                        dim["compliance"]
                        if dim["compliance"] is not None else 1.0,
                        dimension="latency", **labels)
                    burn_g.set(dim["burn_rate"], dimension="latency",
                               **labels)
                dim = SLOTracker._dimension(
                    ok_n, total, self.availability_target)
                row["availability"] = dim
                objective.set(dim["target"], dimension="availability",
                              **labels)
                compliance_g.set(
                    dim["compliance"]
                    if dim["compliance"] is not None else 1.0,
                    dimension="availability", **labels)
                burn_g.set(dim["burn_rate"], dimension="availability",
                           **labels)
                out[name] = row
        return out

    def snapshot(self) -> "Dict[str, dict]":
        with self._lock:
            return {
                name: {
                    "admitted": s.admitted, "shed": s.shed,
                    "completed": s.completed, "failed": s.failed,
                    "weight": s.weight, "priority": s.priority,
                    "bucket": ({"rate": s.bucket.rate,
                                "burst": s.bucket.burst,
                                "tokens": round(s.bucket.tokens, 3)}
                               if s.bucket is not None else None),
                }
                for name, s in self._tenants.items()
            }


# -- brownout ladder ----------------------------------------------------------

#: Ladder levels, in escalation order. Each level keeps the responses
#: of every level below it active.
LEVEL_NORMAL = 0          #: full service
LEVEL_SHED_BACKGROUND = 1  #: PRIORITY_BACKGROUND submits rejected
LEVEL_DEGRADE = 2          #: spec_k / chain_tokens forced to 1
LEVEL_THROTTLE = 3         #: quota'd tenants charged double per admit
LEVEL_REJECT = 4           #: every new submit rejected

LEVEL_NAMES = ("normal", "shed_background", "degrade_quality",
               "throttle_tenants", "reject")


class OverloadController:
    """Process-wide brownout ladder with AutoScaler-style hysteresis.

    ``evaluate(burn_rate=, queue_frac=)`` is the one verb, called on
    the owning engine's tick cadence. The overload *signal* is true
    when either input crosses its threshold; stepping UP one level
    requires the signal to hold ``hysteresis`` consecutive evaluates,
    stepping DOWN requires it quiet for ``recovery_ticks`` consecutive
    evaluates (recovery is deliberately slower — flapping in and out
    of brownout is worse than either state), and every transition is
    followed by ``cooldown_ticks`` evaluates of no movement — the
    exact discipline the AutoTuner/AutoScaler proved out. Transitions
    land in the flight ring (``overload.level``), the
    ``sparkdl_overload_*`` metrics, and the ``overload`` health fact
    ``/healthz`` aggregates (level > 0 reads degraded).
    """

    def __init__(self, *, burn_threshold: float = 2.0,
                 queue_threshold: float = 0.8,
                 hysteresis: int = 2,
                 recovery_ticks: int = 3,
                 cooldown_ticks: int = 2,
                 max_level: int = LEVEL_REJECT,
                 clock: Callable[[], float] = time.monotonic):
        if hysteresis < 1:
            raise ValueError(f"hysteresis must be >= 1, got {hysteresis}")
        if recovery_ticks < 1:
            raise ValueError(
                f"recovery_ticks must be >= 1, got {recovery_ticks}")
        if cooldown_ticks < 0:
            raise ValueError(
                f"cooldown_ticks must be >= 0, got {cooldown_ticks}")
        if not (LEVEL_NORMAL <= max_level <= LEVEL_REJECT):
            raise ValueError(f"max_level must be 0..4, got {max_level}")
        self.burn_threshold = burn_threshold
        self.queue_threshold = queue_threshold
        self.hysteresis = hysteresis
        self.recovery_ticks = recovery_ticks
        self.cooldown_ticks = cooldown_ticks
        self.max_level = max_level
        self._clock = clock
        self._lock = threading.Lock()
        self._level = LEVEL_NORMAL
        self._hot_streak = 0
        self._quiet_streak = 0
        self._cooldown = 0
        self.transitions = 0
        _M_OVERLOAD_LEVEL.set(0)
        self._publish_fact()

    @property
    def level(self) -> int:
        return self._level

    @property
    def level_name(self) -> str:
        return LEVEL_NAMES[self._level]

    def evaluate(self, *, burn_rate: "float | None" = None,
                 queue_frac: "float | None" = None) -> int:
        """One control tick: fold the signals, maybe move one level.
        Returns the (possibly new) level."""
        hot = ((burn_rate is not None
                and burn_rate >= self.burn_threshold)
               or (queue_frac is not None
                   and queue_frac >= self.queue_threshold))
        with self._lock:
            if hot:
                self._hot_streak += 1
                self._quiet_streak = 0
            else:
                self._quiet_streak += 1
                self._hot_streak = 0
            if self._cooldown > 0:
                self._cooldown -= 1
                return self._level
            if (hot and self._hot_streak >= self.hysteresis
                    and self._level < self.max_level):
                self._step_locked(+1, burn_rate, queue_frac)
            elif (not hot and self._quiet_streak >= self.recovery_ticks
                    and self._level > LEVEL_NORMAL):
                self._step_locked(-1, burn_rate, queue_frac)
            return self._level

    def _step_locked(self, direction: int, burn_rate, queue_frac) -> None:
        self._level += direction
        self._hot_streak = 0
        self._quiet_streak = 0
        self._cooldown = self.cooldown_ticks
        self.transitions += 1
        _M_OVERLOAD_LEVEL.set(self._level)
        _M_OVERLOAD_TRANSITIONS.inc(
            direction="up" if direction > 0 else "down")
        flight.record_event(
            "overload.level", level=self._level,
            name=LEVEL_NAMES[self._level],
            direction="up" if direction > 0 else "down",
            burn_rate=burn_rate, queue_frac=queue_frac)
        self._publish_fact()

    def _publish_fact(self) -> None:
        # the /healthz hook: healthz_report reads this fact and calls
        # any level > 0 "degraded" (self-recovering — the ladder steps
        # back down on its own once the signals quiet)
        flight.set_health_fact("overload", {
            "level": self._level,
            "name": LEVEL_NAMES[self._level],
        })

    def admission_check(self, tenant: str, priority: int) -> None:
        """Admission-time ladder enforcement (called by the queue with
        no queue lock held): level >= 1 sheds the background class,
        level 4 sheds everything. Raises :class:`BrownoutShedError`."""
        lvl = self._level
        if lvl >= LEVEL_REJECT:
            _M_OVERLOAD_SHED.inc(level=lvl)
            raise BrownoutShedError(
                lvl, "brownout level 4 (reject): all new submits shed; "
                "retry with backoff")
        if lvl >= LEVEL_SHED_BACKGROUND and priority >= PRIORITY_BACKGROUND:
            _M_OVERLOAD_SHED.inc(level=lvl)
            raise BrownoutShedError(
                lvl, f"brownout level {lvl}: background-class submits "
                f"shed (tenant {tenant!r})")

    def admit_cost(self) -> float:
        """Bucket tokens one admit costs at the current level: level 3+
        charges quota'd tenants double, halving every configured
        tenant's effective rate/burst while the incident lasts."""
        return 2.0 if self._level >= LEVEL_THROTTLE else 1.0

    def degrade_quality(self) -> bool:
        """True at level >= 2: engines cap ``spec_k``/``chain_tokens``
        to 1 (single-token dispatches — lowest latency variance, no
        wasted verify FLOPs while the host is hot)."""
        return self._level >= LEVEL_DEGRADE

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "level": self._level,
                "name": LEVEL_NAMES[self._level],
                "hot_streak": self._hot_streak,
                "quiet_streak": self._quiet_streak,
                "cooldown": self._cooldown,
                "transitions": self.transitions,
            }


# -- process-wide controller hook ---------------------------------------------

_PROCESS_OVERLOAD: "OverloadController | None" = None
_PROCESS_LOCK = threading.Lock()


def set_process_overload(
        ctrl: "OverloadController | None") -> "OverloadController | None":
    """Install (or clear, with None) the process-wide brownout
    controller every queue and engine consults. Returns the previous
    one so tests can restore it."""
    global _PROCESS_OVERLOAD
    with _PROCESS_LOCK:
        prev, _PROCESS_OVERLOAD = _PROCESS_OVERLOAD, ctrl
    if ctrl is None:
        _M_OVERLOAD_LEVEL.set(0)
        flight.set_health_fact("overload", None)
    return prev


def process_overload() -> "OverloadController | None":
    return _PROCESS_OVERLOAD


def overload_level() -> int:
    """The current process-wide brownout level (0 with no controller
    installed — the default, bitwise-identical path)."""
    ctrl = _PROCESS_OVERLOAD
    return ctrl.level if ctrl is not None else LEVEL_NORMAL


def note_preemption() -> None:
    """Count one prefill preemption (the engine's ``tenant.preempt``
    path calls this after the victim re-queued)."""
    _M_PREEMPTIONS.inc()
