"""ServingEngine — the async front door over queue + micro-batcher.

One object to construct, one method to call::

    engine = ServingEngine(
        BatchedRunner(jitted_apply, batch_size=64), max_wait_s=0.004
    )
    fut = engine.submit({"x": row})          # returns immediately
    y = fut.result(timeout=1.0)              # one output row

Requests coalesce into bucketed device batches (dp-sharded on multi-chip
hosts — whatever the wrapped BatchedRunner compiled; or routed whole
over a :class:`~sparkdl_tpu.serving.replicas.ReplicaPool` of per-device
executors); overload rejects at admission (QueueFullError), deadlines
cancel mid-queue (DeadlineExceededError), and ``close(drain=True)``
serves every admitted request before stopping.

Observability (ISSUE 9): every submit allocates a request id
(``fut.request_id``); with ``SPARKDL_TPU_TRACE=1`` the request's full
span set replays via :meth:`ServingEngine.trace`. Pass ``slo=`` to
declare latency/availability objectives — rolling error-budget burn then
rides ``snapshot()["slo"]``, the ``sparkdl_slo_*`` gauges, and the
exporter's ``/slo.json``. The engine also registers itself with the
flight recorder, so reliability-triggered postmortem bundles carry its
queue state and in-flight request traces.
"""

from __future__ import annotations

from concurrent.futures import Future
from typing import Any, Callable

import numpy as np

from sparkdl_tpu.observability import slo as slo_mod
from sparkdl_tpu.observability import tracing
from sparkdl_tpu.observability.exporters import maybe_start_metrics_server
from sparkdl_tpu.serving import tenancy
from sparkdl_tpu.serving.metrics import EngineObservability, ServingMetrics
from sparkdl_tpu.serving.microbatcher import MicroBatcher
from sparkdl_tpu.serving.queue import RequestQueue
from sparkdl_tpu.transformers._inference import BatchedRunner


class ServingEngine:
    """Online micro-batching inference over a :class:`BatchedRunner` or
    a :class:`~sparkdl_tpu.serving.replicas.ReplicaPool` (anything with
    the ``run_batch``/``run_batch_async``/``chunk_size`` surface).

    ``max_wait_s`` bounds the extra latency the FIRST request of a batch
    pays to pick up riders; ``max_queue_depth`` bounds host memory and
    turns overload into fast rejects instead of unbounded tail latency.
    ``slo`` (an :class:`~sparkdl_tpu.observability.slo.SLO`) declares
    this engine's objectives; the tracker it creates lives on
    ``self.slo_tracker`` and is unregistered at close.
    """

    def __init__(self, runner: "BatchedRunner | Any", *,
                 max_queue_depth: int = 256,
                 max_wait_s: float = 0.005,
                 extract: Callable[[Any], dict[str, np.ndarray]] | None = None,
                 metrics: ServingMetrics | None = None,
                 slo: "slo_mod.SLO | None" = None,
                 tenants: "tenancy.TenantRegistry | None" = None,
                 host_id: "str | None" = None):
        from sparkdl_tpu.serving.metrics import default_host_id

        # Opt-in observability endpoint (SPARKDL_TPU_METRICS_PORT):
        # idempotent, so every engine in the process shares one server.
        maybe_start_metrics_server()
        self.runner = runner
        #: stable host identity for the fabric's router tier (ISSUE 14)
        self.host_id = host_id if host_id is not None else default_host_id()
        self.queue = RequestQueue(max_depth=max_queue_depth,
                                  tenants=tenants)
        self.metrics = metrics if metrics is not None else ServingMetrics()
        self.batcher = MicroBatcher(
            self.queue, runner, max_wait_s=max_wait_s, extract=extract,
            metrics=self.metrics,
        ).start()
        # process-wide registrations go LAST: a constructor failure above
        # must not leak a tracker/provider bound to a half-built engine
        self._obs = EngineObservability(
            "engine", self._flight_context, slo=slo,
            max_queue_depth=max_queue_depth,
        )
        self.slo_tracker = self._obs.tracker

    def submit(self, payload: Any, *,
               timeout_s: float | None = None,
               tenant: str = "default",
               priority: "int | None" = None) -> Future:
        """Admit one request (a feature dict of per-row arrays, or
        whatever ``extract`` eats). Returns a Future resolving to the
        output row (carrying ``request_id``); raises QueueFullError /
        EngineClosedError at the door. ``tenant``/``priority`` scope the
        request for quota and class scheduling (ISSUE 20; the defaults
        reproduce the single-user path) — over-quota and brownout sheds
        raise the typed :mod:`~sparkdl_tpu.serving.tenancy` errors."""
        return self.queue.submit(payload, timeout_s=timeout_s,
                                 tenant=tenant, priority=priority)

    def trace(self, request_id: int) -> "list[dict]":
        """Every finished span of one request's trace (queue wait, batch
        assembly/dispatch via links, replica execution, the terminal
        ``serving.request``), timestamp-ordered. Empty with tracing off —
        enable with ``SPARKDL_TPU_TRACE=1`` or
        :func:`~sparkdl_tpu.observability.tracing.enable_tracing`.
        Export for Perfetto with
        ``tracing.export_chrome_trace(path, trace_id=request_id)``."""
        return tracing.spans_for_trace(request_id)

    def inflight_request_ids(self) -> "list[int]":
        """Ids of every admitted-but-unresolved request (queued +
        dispatched) — what a postmortem bundle resolves to traces."""
        return (self.queue.pending_request_ids()
                + self.batcher.inflight_request_ids())

    def begin_drain(self):
        """Graceful host drain, phase one (ISSUE 14): stop admission and
        hand back every accepted-but-undispatched request (the fabric
        re-queues them to surviving hosts via ``RequestQueue.requeue``
        on the target — Futures, trace ids, and deadlines untouched).
        Batches already dispatched finish here; :meth:`close` afterwards
        completes the drain."""
        from sparkdl_tpu.observability import flight

        self.queue.close()
        reqs = self.queue.extract_pending()
        flight.record_event(
            "engine.drain_begin", engine=self._obs.name,
            host=self.host_id, extracted=len(reqs))
        return reqs

    def capacity(self, _pool_snap: "dict | None" = None) -> dict:
        """The one structure a router's weighting reads (ISSUE 14):
        identity + room. ``n_slots``/KV fields are None — this engine
        has no persistent decode slots or block pool; its weight is its
        replica count. ``_pool_snap`` lets :meth:`snapshot` share the
        pool snapshot it already fetched (walking per-replica state
        twice per router poll would be pure waste)."""
        if _pool_snap is None:
            pool_snapshot = getattr(self.runner, "snapshot", None)
            _pool_snap = (pool_snapshot()
                          if callable(pool_snapshot) else {})
        replicas = _pool_snap.get("replica_count", 1)
        return {
            "host_id": self.host_id,
            "replica_count": replicas,
            "n_slots": None,
            "free_slots": None,
            "kv_blocks_free": None,
            "kv_blocks_total": None,
            "queue_depth": self.queue.depth,
            "max_queue_depth": self.queue.max_depth,
            "draining": self.queue.closed,
            "overload_level": tenancy.overload_level(),
        }

    def prefix_digest(self, max_entries: int = 1024) -> "dict | None":
        """No prefix cache on the micro-batching engine: routing to it
        is pure load balancing (the fabric's digest surface is uniform
        across host kinds, so the router never special-cases)."""
        return None

    def close(self, *, drain: bool = True,
              timeout_s: float | None = 30.0) -> None:
        self.batcher.shutdown(drain=drain, timeout_s=timeout_s)
        self._obs.close(drain=drain)

    def _flight_context(self) -> dict:
        """The engine's contribution to flight-recorder postmortems."""
        out = self.metrics.snapshot(self.queue)
        out["inflight_request_ids"] = self.inflight_request_ids()
        if self.slo_tracker is not None:
            out["slo"] = self.slo_tracker.sample()
        return out

    def snapshot(self) -> dict:
        """Operator metrics: queue depth, occupancy, latency p50/p95/p99,
        admission counters — plus per-replica depth/in-flight/quarantine
        state when the runner is a ReplicaPool, the process-wide
        shed-load breakdown (``requests_failed_by_reason``, from the
        reliability layer's ``sparkdl_requests_failed_total`` counter),
        and rolling SLO compliance/burn under ``slo`` when objectives
        were declared."""
        snap = self.metrics.snapshot(self.queue)
        snap["host_id"] = self.host_id
        pool_snapshot = getattr(self.runner, "snapshot", None)
        pool_snap = pool_snapshot() if callable(pool_snapshot) else None
        snap["capacity"] = self.capacity(_pool_snap=pool_snap or {})
        if pool_snap is not None:
            snap.update(pool_snap)
        else:
            snap["replica_count"] = 1
        from sparkdl_tpu.observability.registry import registry

        fam = registry().get("sparkdl_requests_failed_total")
        snap["requests_failed_by_reason"] = (
            fam.labelled_values("reason") if fam else {}
        )
        snap["slo"] = (self.slo_tracker.sample()
                       if self.slo_tracker is not None else None)
        return snap

    def __enter__(self) -> "ServingEngine":
        return self

    def __exit__(self, *exc) -> None:
        self.close(drain=exc == (None, None, None))
