"""Composable streaming-ingest pipeline (ROADMAP item 2, tf.data-style).

One declarative chain replaces the ad-hoc hand-wired infeeds::

    Pipeline(jpeg_bytes, name="hostfed")
        .map(decode, parallelism=None)        # ordered parallel host work
        .batch(128)                           # bucketing batching
        .to_device(transfer)                  # native ring / prefetch

Stages:

* :meth:`Pipeline.map` — ordered parallel map on a thread pool; the
  window of in-flight items IS the parallelism and is live-resizable
  (the autotuner's ``map_parallelism`` knob).
* :meth:`Pipeline.interleave` — round-robin over ``cycle`` open
  sub-iterators (tf.data ``interleave``): overlap per-source latency
  (file opens, shard fetches) without reordering within a source.
* :meth:`Pipeline.batch` — bucketed batching via
  :func:`~sparkdl_tpu.runtime.batching.rebatch` (dict rows ->
  :class:`~sparkdl_tpu.runtime.batching.PaddedBatch`).
* :meth:`Pipeline.prefetch` — background-thread readahead
  (:class:`~sparkdl_tpu.runtime.prefetch.PrefetchIterator`), depth
  live-resizable without dropping staged batches.
* :meth:`Pipeline.to_device` — the host->device hand-off: the native
  staging ring (:class:`~sparkdl_tpu.native.bridge.DeviceFeeder`) for
  uniform feeds when the .so is built, the Python prefetcher otherwise —
  exactly the selection :class:`~sparkdl_tpu.transformers._inference.
  BatchedRunner` has always made, now a reusable stage.

``.autotune(...)`` hands every stage's knobs to an
:class:`~sparkdl_tpu.ingest.autotune.AutoTuner`; explicitly configured
stage values register pinned (never moved). A pipeline is one-shot: it
iterates its source once; ``close()`` (also on exhaustion and
context-manager exit) releases threads and unregisters knobs.
"""

from __future__ import annotations

import itertools
import os
import threading
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Iterable, Iterator, Sequence

from sparkdl_tpu.ingest.autotune import (
    AutoTuner,
    Knob,
    autotune_enabled,
    default_tuner,
)

__all__ = ["Pipeline", "resolve_pin", "unique_name"]

_PIPE_IDS = itertools.count(1)


def unique_name(prefix: str) -> str:
    """A process-unique pipeline name with a readable prefix — use for
    knob-exporting pipelines constructed per stream (e.g. each
    ``BatchedRunner.run``), so concurrent streams never collide in the
    tuner's name-keyed registry."""
    return f"{prefix}{next(_PIPE_IDS)}"


def resolve_pin(
    explicit: "int | None",
    env_var: "str | None",
    default: int,
    *,
    what: str,
) -> "tuple[int, bool, str | None]":
    """Resolve one knob's configured value against its env pin.

    Returns ``(value, pinned, pin_source)``. An explicit argument pins;
    a set env var pins; BOTH set and disagreeing is a conflicting-pin
    misconfiguration and raises rather than silently preferring one.
    """
    env_val: "int | None" = None
    if env_var:
        raw = os.environ.get(env_var)
        if raw:
            env_val = int(raw)
            if env_val < 1:
                raise ValueError(
                    f"{env_var} must be >= 1, got {raw!r}")
    if explicit is not None and explicit < 0:
        raise ValueError(f"{what} must be >= 0, got {explicit}")
    if explicit is not None and env_val is not None and explicit != env_val:
        raise ValueError(
            f"conflicting pins for {what}: explicit {explicit} vs "
            f"{env_var}={env_val} — pin it one way, not both"
        )
    if explicit is not None:
        return explicit, True, what
    if env_val is not None:
        return env_val, True, env_var
    return default, False, None


# ---------------------------------------------------------------------------
# Stage implementations
# ---------------------------------------------------------------------------


class _ParallelMapIter(Iterator[Any]):
    """Ordered parallel map: keep up to ``parallelism`` calls in flight,
    yield results in submission order (bitwise-identical stream to a
    plain ``map``). ``parallelism`` is a live attribute — the autotuner
    resizes the in-flight window between takes; the pool is sized at the
    ``hi`` bound once so resizing never spawns/joins threads mid-stream.
    """

    def __init__(self, src: Iterator[Any], fn: Callable[[Any], Any],
                 parallelism: int, hi: int, name: str):
        self._src = src
        self._fn = fn
        self.parallelism = max(1, parallelism)
        self._hi = hi
        self._pool = ThreadPoolExecutor(
            max_workers=hi, thread_name_prefix=f"sparkdl-ingest-{name}")
        self._pending: deque = deque()
        self._exhausted = False
        self._closed = False

    def _top_up(self) -> None:
        window = max(1, min(int(self.parallelism), self._hi))
        while not self._exhausted and len(self._pending) < window:
            try:
                item = next(self._src)
            except StopIteration:
                self._exhausted = True
                break
            self._pending.append(self._pool.submit(self._fn, item))

    def __iter__(self) -> "_ParallelMapIter":
        return self

    def __next__(self) -> Any:
        if self._closed:
            raise StopIteration
        self._top_up()
        if not self._pending:
            self.close()
            raise StopIteration
        fut = self._pending.popleft()
        # refill BEFORE blocking so the window stays full while this
        # result is still cooking
        self._top_up()
        try:
            return fut.result()
        except BaseException:
            self.close()
            raise

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        for f in self._pending:
            f.cancel()
        self._pending.clear()
        self._pool.shutdown(wait=False)


class _InterleaveIter(Iterator[Any]):
    """Round-robin over ``cycle`` open sub-iterators (tf.data
    ``interleave``): each source item opens one sub-iterator via
    ``make_iter``; takes cycle across the open set, refilling from the
    source as sub-iterators exhaust. Deterministic for deterministic
    inputs."""

    def __init__(self, src: Iterator[Any],
                 make_iter: Callable[[Any], Iterable[Any]], cycle: int):
        self._src = src
        self._make = make_iter
        self.cycle = max(1, cycle)
        self._active: "list[Iterator[Any]]" = []
        self._idx = 0
        self._exhausted = False

    def __iter__(self) -> "_InterleaveIter":
        return self

    def _fill(self) -> None:
        while not self._exhausted and len(self._active) < max(1, self.cycle):
            try:
                item = next(self._src)
            except StopIteration:
                self._exhausted = True
                break
            self._active.append(iter(self._make(item)))

    def __next__(self) -> Any:
        while True:
            self._fill()
            if not self._active:
                raise StopIteration
            i = self._idx % len(self._active)
            try:
                v = next(self._active[i])
            except StopIteration:
                del self._active[i]
                self._idx = i
                continue
            self._idx = i + 1
            return v


# ---------------------------------------------------------------------------
# Stage descriptors
# ---------------------------------------------------------------------------


class _Stage:
    name: str

    def build(self, src: Iterator[Any], pipe: "Pipeline") -> Iterator[Any]:
        raise NotImplementedError

    def knobs(self, prefix: str) -> "list[Knob]":
        return []

    def close(self) -> None:
        pass


class _MapStage(_Stage):
    def __init__(self, fn, parallelism, max_parallelism, env_var, name):
        self.name = name
        self._fn = fn
        value, pinned, source = resolve_pin(
            parallelism, env_var, 1, what=f"{name}.parallelism")
        self._start = max(1, value)
        self._pinned = pinned
        self._pin_source = source
        self._hi = max(max_parallelism, self._start)
        self._live: "_ParallelMapIter | None" = None

    def build(self, src, pipe):
        self._live = _ParallelMapIter(
            src, self._fn, self._start, self._hi, self.name)
        return self._live

    def knobs(self, prefix):
        live = self._live
        if live is None:
            return []

        def set_par(v: int, live=live) -> None:
            live.parallelism = v

        return [Knob(
            name=f"{prefix}.{self.name}_parallelism",
            get=lambda live=live: int(live.parallelism),
            set=set_par, lo=1, hi=self._hi,
            pinned=self._pinned, pin_source=self._pin_source,
        )]

    def close(self):
        if self._live is not None:
            self._live.close()


class _InterleaveStage(_Stage):
    def __init__(self, make_iter, cycle, name):
        self.name = name
        self._make = make_iter
        self._cycle = cycle

    def build(self, src, pipe):
        return _InterleaveIter(src, self._make, self._cycle)


class _BatchStage(_Stage):
    def __init__(self, batch_size, buckets, name):
        self.name = name
        self._batch_size = batch_size
        self._buckets = buckets

    def build(self, src, pipe):
        from sparkdl_tpu.runtime.batching import rebatch

        return rebatch(src, self._batch_size, self._buckets)


class _TapStage(_Stage):
    """Zero-cost inline observer (``fn(item)`` per item, item passed
    through) — how a consumer records per-batch metadata (``n_valid``)
    without forking the stream."""

    def __init__(self, fn, name):
        self.name = name
        self._fn = fn

    def build(self, src, pipe):
        fn = self._fn

        def gen():
            for item in src:
                fn(item)
                yield item

        return gen()


class _ApplyStage(_Stage):
    """Synchronous inline transform (no thread pool, no readahead):
    for stages that must stay strictly consumer-pulled, e.g. unwrapping
    a ``PaddedBatch`` into its arrays between batch and to_device."""

    def __init__(self, fn, name):
        self.name = name
        self._fn = fn

    def build(self, src, pipe):
        return map(self._fn, src)


class _PrefetchStage(_Stage):
    def __init__(self, depth, transfer, env_var, name, pinned=None):
        self.name = name
        value, auto_pinned, source = resolve_pin(
            depth, env_var, 2, what=f"{name}.depth")
        #: 0 = readahead disabled: the stage passes through (strictly
        #: consumer-pulled, no producer thread) — same contract as
        #: finetune's input_prefetch=0
        self._depth = max(0, value)
        self._pinned = auto_pinned if pinned is None else pinned
        self._pin_source = source
        self._transfer = transfer
        self._live = None

    def build(self, src, pipe):
        if self._depth == 0:
            if self._transfer is None:
                return src
            return map(self._transfer, src)
        from sparkdl_tpu.runtime.prefetch import PrefetchIterator

        self._live = PrefetchIterator(
            src, size=self._depth, transfer=self._transfer)
        return self._live

    def knobs(self, prefix):
        live = self._live
        if live is None:
            return []
        return [Knob(
            name=f"{prefix}.{self.name}_depth",
            get=lambda live=live: int(live.depth),
            set=lambda v, live=live: live.set_depth(v),
            lo=1, hi=64,
            pinned=self._pinned, pin_source=self._pin_source,
        )]

    def close(self):
        if self._live is not None:
            self._live.close()


class _ToDeviceStage(_Stage):
    """Host->device staging with transfer/compute overlap: the native
    struct-of-tensors staging ring for uniform feeds, the Python
    prefetcher for ragged feeds or hosts without the .so — the
    BatchedRunner feed policy as a composable stage.

    ``depth``: batches in flight ahead of the consumer (the ring runs
    ``depth + 1`` slots: one being consumed plus ``depth`` staged).
    ``max_bucket``: rows to size ring slot segments for (the largest
    bucket a batch can pad to); None sizes from the first batch.
    On the Python path the depth knob resizes live; the ring's slot
    count is fixed per stream, so there the knob updates the
    process-level suggestion the NEXT stream is built with
    (:func:`sparkdl_tpu.native.bridge.set_tuned_ring_slots`).
    """

    def __init__(self, transfer, depth, ragged, max_bucket, env_var, name,
                 pinned=None, lo=1):
        self.name = name
        value, auto_pinned, source = resolve_pin(
            depth, env_var, 2, what=f"{name}.depth")
        self._depth = max(1, value)
        self._pinned = auto_pinned if pinned is None else pinned
        self._pin_source = source
        #: depth floor under tuning (a consumer's chain ceiling: depth
        #: below it makes chain assembly the serialization point)
        self._lo = max(1, lo)
        self._transfer = transfer
        self._ragged = ragged
        self._max_bucket = max_bucket
        self._live_prefetch = None
        self._on_ring = False
        self._gen = None

    def build(self, src, pipe):
        # The ring-vs-prefetch decision happens EAGERLY (it needs the
        # first batch's dtypes/shapes anyway) so knob registration —
        # which runs right after build — sees which path is live.
        from sparkdl_tpu.native.bridge import native_available
        from sparkdl_tpu.runtime.prefetch import PrefetchIterator

        it = iter(src)
        try:
            first = next(it)
        except StopIteration:
            self._gen = iter(())
            return self._gen
        if (native_available() and not self._ragged
                and isinstance(first, dict)):
            self._on_ring = True
            self._gen = self._ring_feed(first, it)
        else:
            def stream():
                yield first
                yield from it

            self._live_prefetch = PrefetchIterator(
                stream(), size=self._depth, transfer=self._transfer)
            self._gen = self._live_prefetch
        return self._gen

    def _ring_feed(self, first, it):
        from sparkdl_tpu.native.bridge import DeviceFeeder, tuned_ring_slots

        def stream():
            yield first
            yield from it

        # segments sized for the LARGEST bucket; the first batch may
        # be a smaller tail bucket
        rows = max(next(iter(first.values())).shape[0], 1)
        bucket = self._max_bucket or rows
        seg = {
            k: (first[k].nbytes // max(first[k].shape[0], 1)) * bucket
            for k in first
        }
        n_slots = tuned_ring_slots(self._depth + 1)
        yield from DeviceFeeder(
            stream(), n_slots=n_slots, max_batch_bytes=seg,
            transfer=self._transfer,
        )

    def knobs(self, prefix):
        if self._live_prefetch is not None:
            live = self._live_prefetch
            return [Knob(
                name=f"{prefix}.{self.name}_depth",
                get=lambda live=live: int(live.depth),
                set=lambda v, live=live: live.set_depth(v),
                lo=self._lo, hi=max(64, self._lo),
                pinned=self._pinned, pin_source=self._pin_source,
            )]
        if self._on_ring:
            from sparkdl_tpu.native import bridge

            return [Knob(
                name=f"{prefix}.{self.name}_ring_slots",
                get=lambda d=self._depth: int(
                    bridge.tuned_ring_slots(d + 1)),
                set=bridge.set_tuned_ring_slots,
                # slots = depth + 1 (one consuming + depth staged), so
                # the floor rides one above the depth floor
                lo=max(2, self._lo + 1), hi=max(16, self._lo + 1),
                pinned=self._pinned, pin_source=self._pin_source,
            )]
        return []

    def close(self):
        if self._live_prefetch is not None:
            self._live_prefetch.close()
        elif self._gen is not None and hasattr(self._gen, "close"):
            self._gen.close()


# ---------------------------------------------------------------------------
# The pipeline
# ---------------------------------------------------------------------------


class Pipeline(Iterable[Any]):
    """Declarative stage chain over one source; see module docstring.

    ``source`` is any iterable (consumed once). ``name`` prefixes the
    knob names this pipeline exports (``<name>.<stage>_<knob>``) so
    multiple pipelines tune independently in one registry.
    """

    def __init__(self, source: Iterable[Any], *, name: "str | None" = None):
        self._source = source
        self.name = name or f"pipe{next(_PIPE_IDS)}"
        self._stages: "list[_Stage]" = []
        self._tuner: "AutoTuner | None" = None
        self._tuner_started_here = False
        self._registered: "list[Knob]" = []
        self._extra_knobs: "list[Knob]" = []
        self._live = False
        self._closed = False
        self._lock = threading.Lock()

    # -- stage builders ------------------------------------------------------
    def map(self, fn: Callable[[Any], Any], *,
            parallelism: "int | None" = None, max_parallelism: int = 8,
            env_var: "str | None" = None, name: str = "map") -> "Pipeline":
        """Ordered parallel map. ``parallelism=None`` starts at 1 and is
        autotunable up to ``max_parallelism``; an explicit value (or a
        set ``env_var``) pins it."""
        self._stages.append(
            _MapStage(fn, parallelism, max_parallelism, env_var, name))
        return self

    def interleave(self, make_iter: Callable[[Any], Iterable[Any]], *,
                   cycle: int = 2, name: str = "interleave") -> "Pipeline":
        """Round-robin interleave of ``cycle`` sub-iterators opened by
        ``make_iter`` over consecutive source items."""
        self._stages.append(_InterleaveStage(make_iter, cycle, name))
        return self

    def batch(self, batch_size: int,
              buckets: "Sequence[int] | None" = None, *,
              name: str = "batch") -> "Pipeline":
        """Bucketed batching: dict rows -> ``PaddedBatch`` (static
        shapes for XLA, one compile per bucket)."""
        self._stages.append(_BatchStage(batch_size, buckets, name))
        return self

    def tap(self, fn: Callable[[Any], None], *,
            name: str = "tap") -> "Pipeline":
        self._stages.append(_TapStage(fn, name))
        return self

    def apply(self, fn: Callable[[Any], Any], *,
              name: str = "apply") -> "Pipeline":
        """Synchronous inline transform (use :meth:`map` for host work
        worth parallelizing; this one adds zero threads or readahead)."""
        self._stages.append(_ApplyStage(fn, name))
        return self

    def prefetch(self, depth: "int | None" = None, *,
                 transfer: "Callable | None" = None,
                 env_var: "str | None" = None,
                 pinned: "bool | None" = None,
                 name: str = "prefetch") -> "Pipeline":
        """Background-thread readahead ``depth`` deep (default 2,
        autotunable; explicit/env pins — override with ``pinned`` when
        the caller resolved pin-ness itself; ``0`` disables readahead:
        the stage passes through strictly consumer-pulled, applying
        ``transfer`` inline). ``transfer`` runs on the producer thread
        (default ``jax.device_put``; pass ``lambda x: x`` for pure host
        readahead)."""
        self._stages.append(
            _PrefetchStage(depth, transfer, env_var, name, pinned))
        return self

    def to_device(self, transfer: "Callable | None" = None, *,
                  depth: "int | None" = None, ragged: bool = False,
                  max_bucket: "int | None" = None,
                  env_var: "str | None" = None,
                  pinned: "bool | None" = None,
                  lo: int = 1,
                  name: str = "device") -> "Pipeline":
        """Stage batches onto the device: native ring when it applies,
        Python prefetch otherwise (see :class:`_ToDeviceStage`). ``lo``
        floors the tuned depth (pass a consumer's chain ceiling so the
        tuner can never shrink staging below one chain's worth)."""
        self._stages.append(
            _ToDeviceStage(transfer, depth, ragged, max_bucket, env_var,
                           name, pinned, lo))
        return self

    # -- tuning --------------------------------------------------------------
    def autotune(self, enabled: "bool | AutoTuner | None" = True,
                 extra_knobs: "Iterable[Knob] | None" = None) -> "Pipeline":
        """Attach this pipeline's knobs to a tuner when iteration
        starts. ``True`` (or ``None`` with ``SPARKDL_TPU_AUTOTUNE`` set)
        uses (and starts) the process :func:`default_tuner`; ``False``
        detaches unconditionally — an explicit opt-out beats the env
        var. Pass an :class:`AutoTuner` to supply your own (it is NOT
        auto-started — drive ``tick()`` or ``start()`` yourself).
        ``extra_knobs`` ride along (e.g. a consumer's dispatch chain-K)
        and unregister with the pipeline's own."""
        if isinstance(enabled, AutoTuner):
            self._tuner = enabled
        elif enabled is False:
            self._tuner = None
            self._tuner_started_here = False
        elif autotune_enabled(enabled):
            self._tuner = default_tuner()
            self._tuner_started_here = True
        if extra_knobs is not None:
            self._extra_knobs.extend(extra_knobs)
        return self

    @property
    def tuner(self) -> "AutoTuner | None":
        return self._tuner

    # -- execution -----------------------------------------------------------
    def __iter__(self) -> Iterator[Any]:
        with self._lock:
            if self._live or self._closed:
                raise RuntimeError(
                    f"pipeline {self.name!r} is one-shot: it already "
                    "iterated (build a new Pipeline per pass)"
                )
            self._live = True
        it: Iterator[Any] = iter(self._source)
        for stage in self._stages:
            it = iter(stage.build(it, self))
        if self._tuner is not None:
            for stage in self._stages:
                for knob in stage.knobs(self.name):
                    self._tuner.register(knob)
                    self._registered.append(knob)
            for knob in self._extra_knobs:
                self._tuner.register(knob)
                self._registered.append(knob)
            if self._tuner_started_here:
                self._tuner.start()

        def run():
            try:
                yield from it
            finally:
                self.close()

        return run()

    def close(self) -> None:
        """Release stage threads/buffers and unregister knobs.
        Idempotent; also runs on exhaustion and ``with`` exit."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        if self._tuner is not None:
            for knob in self._registered:
                # identity-checked: a successor stream that re-used the
                # name keeps its live knob
                self._tuner.unregister(knob.name, knob)
            self._registered = []
        for stage in reversed(self._stages):
            try:
                stage.close()
            except Exception:
                pass

    def __enter__(self) -> "Pipeline":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
