"""Autotuned streaming ingest (ROADMAP item 2; tf.data, arXiv 2101.12127).

:class:`Pipeline` — composable ``source -> map(fn, parallelism) ->
interleave(cycle) -> batch(bucketing) -> prefetch(depth) / to_device``
stage chain subsuming the hand-wired infeeds.

:class:`AutoTuner` / :class:`Knob` — the online control loop that closes
the observability spine back onto the knobs: starvation grows the
producer side, producer blocking shrinks it (and grows inverted
consumer-side knobs like the dispatch chain K), bounded power-of-two
steps with hysteresis so it never oscillates. Explicit settings pin.
"""

from sparkdl_tpu.ingest.autotune import (
    AutoTuner,
    Knob,
    autotune_enabled,
    autotune_telemetry,
    default_tuner,
    read_feed_signals,
)
from sparkdl_tpu.ingest.pipeline import Pipeline, resolve_pin, unique_name

__all__ = [
    "AutoTuner",
    "Knob",
    "Pipeline",
    "autotune_enabled",
    "autotune_telemetry",
    "default_tuner",
    "read_feed_signals",
    "resolve_pin",
    "unique_name",
]
