"""Online autotuner: closes the control loop from the observability spine.

The tf.data AUTOTUNE idea (Murray et al., arXiv 2101.12127) applied to
this runtime's ingest knobs: the registry already measures the two sides
of every producer/consumer hand-off —

* **starvation** — consumer time blocked waiting on the feed
  (``sparkdl_prefetch_consumer_wait_seconds`` and the ring twin
  ``sparkdl_ring_consumer_wait_seconds``): the producer side is the
  bottleneck, so producer-side knobs (prefetch depth, map parallelism,
  ring slots, pack threads) should GROW;
* **producer blocking** — producer time blocked on a full buffer
  (``sparkdl_prefetch_producer_blocked_seconds_total`` and
  ``sparkdl_ring_slot_wait_seconds_total``): the consumer side is the
  bottleneck, so producer-side knobs shrink back (freeing memory) while
  consumer-side knobs (the dispatch chain K — marked ``inverted``) grow
  to amortize per-dispatch overhead.

The loop is a bounded hill-climb with hysteresis: a direction must hold
for ``hysteresis`` consecutive samples before any knob moves, every move
is one power-of-two step clamped to ``[lo, hi]``, and a post-move
``cooldown`` lets the change take effect before the next decision — so
the tuner cannot oscillate on a noisy signal. Explicitly configured
knobs (``prefetch=``, ``SPARKDL_TPU_CHAIN_K``, ...) register as *pinned*
and are never moved.

Every decision is observable: ``sparkdl_autotune_decisions_total
{knob,direction}``, the current value gauge ``sparkdl_autotune_knob
{knob}``, ``sparkdl_autotune_ticks_total``, and an ``autotune.decision``
span per applied move — the same spine the tuner reads from records what
it did, so a bench artifact carries the full decision history.

Determinism for tests: the sample clock and the signal reader are both
injectable, and ``tick()`` may be driven manually instead of via the
cadence thread (:meth:`AutoTuner.start`).
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from typing import Callable, Iterable

from sparkdl_tpu.observability import flight, tracing
from sparkdl_tpu.observability.registry import registry

__all__ = [
    "AutoTuner",
    "Knob",
    "autotune_enabled",
    "default_tuner",
    "read_feed_signals",
]

_METRICS = None


def _metrics():
    global _METRICS
    if _METRICS is None:
        _METRICS = (
            registry().counter(
                "sparkdl_autotune_decisions_total",
                "autotuner knob moves applied",
                labels=("knob", "direction")),
            registry().gauge(
                "sparkdl_autotune_knob",
                "current value of each autotuned knob",
                labels=("knob",)),
            registry().counter(
                "sparkdl_autotune_ticks_total",
                "autotuner control-loop samples taken"),
        )
    return _METRICS


@dataclasses.dataclass
class Knob:
    """One tunable integer setting.

    ``get``/``set`` close over the live object (a prefetch iterator's
    depth, a chainer's K, a module-level suggestion). ``inverted`` marks
    consumer-side knobs that move OPPOSITE the producer-side direction:
    when the feed starves the consumer, producer knobs grow while an
    inverted knob (dispatch chain K) shrinks toward its floor, and vice
    versa. ``pinned`` knobs are registered for visibility (the gauge
    still exports their value) but never moved; ``pin_source`` records
    why (the argument or env var that pinned it) for fail-loud conflict
    messages.
    """

    name: str
    get: Callable[[], int]
    set: Callable[[int], None]
    lo: int
    hi: int
    pinned: bool = False
    pin_source: "str | None" = None
    inverted: bool = False

    def __post_init__(self):
        if self.lo < 1 or self.hi < self.lo:
            raise ValueError(
                f"knob {self.name}: need 1 <= lo <= hi, got "
                f"[{self.lo}, {self.hi}]"
            )


def _pow2_step(cur: int, direction: int, lo: int, hi: int) -> int:
    """One bounded multiplicative step: double up / halve down, clamped.
    Powers-of-two moves keep jit-cache churn bounded for shape-keyed
    knobs (chain K) and converge in log2(hi/lo) decisions for the rest."""
    if direction > 0:
        return min(hi, max(cur + 1, cur * 2))
    return max(lo, cur // 2)


#: cumulative feed signals: (consumer-starved seconds, producer-blocked
#: seconds, items delivered) summed over the python-prefetch and
#: native-ring paths. The items counter gives the tuner an OBJECTIVE:
#: a move that shrinks delivered throughput gets reverted, whatever the
#: bottleneck shares said.
def read_feed_signals() -> "tuple[float, float, float]":
    """Read the cumulative starvation / producer-blocked seconds and the
    delivered-item count from the registry — the exact series
    ``/metrics`` exposes, no tuner-local bookkeeping."""
    snap_starve = 0.0
    snap_blocked = 0.0
    snap_items = 0.0
    reg = registry()
    for name in ("sparkdl_prefetch_consumer_wait_seconds",
                 "sparkdl_ring_consumer_wait_seconds"):
        fam = reg.get(name)
        if fam is None:
            continue
        for v in fam.snapshot_values().values():
            if isinstance(v, dict):
                snap_starve += float(v.get("sum") or 0.0)
    for name in ("sparkdl_prefetch_producer_blocked_seconds_total",
                 "sparkdl_ring_slot_wait_seconds_total"):
        fam = reg.get(name)
        if fam is None:
            continue
        for v in fam.snapshot_values().values():
            if isinstance(v, (int, float)):
                snap_blocked += float(v)
    for name in ("sparkdl_prefetch_batches_total",
                 "sparkdl_ring_batches_total"):
        fam = reg.get(name)
        if fam is None:
            continue
        for v in fam.snapshot_values().values():
            if isinstance(v, (int, float)):
                snap_items += float(v)
    return snap_starve, snap_blocked, snap_items


class AutoTuner:
    """Samples the feed signals at a fixed cadence and hill-climbs the
    registered knobs. See the module docstring for the control law.

    Thresholds: a sample's *starvation share* (starved seconds / elapsed
    wall) above ``starve_hi`` votes to grow the producer side; a
    *blocked share* above ``blocked_hi`` with starvation below
    ``starve_lo`` votes to shrink it. Anything else is a neutral sample
    and resets the streak — only ``hysteresis`` consecutive same-
    direction votes move knobs, and after a move ``cooldown_ticks``
    samples are skipped so the change's effect is what the next vote
    sees.

    Objective feedback: when the signal reader supplies a delivered-item
    counter, the first sample after a move's cooldown compares delivered
    throughput against the pre-move rate — a drop beyond
    ``revert_tolerance`` reverts the move and puts that direction on a
    ``tabu_ticks`` blocklist, so a move that the bottleneck shares
    suggested but the throughput refutes (e.g. chaining dispatches on a
    backend with a negligible dispatch gap) is undone once and not
    retried every few samples.
    """

    def __init__(
        self,
        *,
        interval_s: float = 0.25,
        hysteresis: int = 2,
        cooldown_ticks: int = 2,
        starve_hi: float = 0.10,
        starve_lo: float = 0.02,
        blocked_hi: float = 0.10,
        revert_tolerance: float = 0.2,
        tabu_ticks: int = 50,
        clock: Callable[[], float] = time.monotonic,
        signals: "Callable[[], tuple] | None" = None,
    ):
        if hysteresis < 1:
            raise ValueError(f"hysteresis must be >= 1, got {hysteresis}")
        self.interval_s = interval_s
        self.hysteresis = hysteresis
        self.cooldown_ticks = cooldown_ticks
        self.starve_hi = starve_hi
        self.starve_lo = starve_lo
        self.blocked_hi = blocked_hi
        self.revert_tolerance = revert_tolerance
        self.tabu_ticks = tabu_ticks
        self._clock = clock
        self._signals = signals if signals is not None else read_feed_signals
        self._lock = threading.Lock()
        self._knobs: "dict[str, Knob]" = {}
        #: (now, starve, blocked, items|None) of the previous sample
        self._last_sample: "tuple | None" = None
        self._streak_dir = 0
        self._streak = 0
        self._cooldown = 0
        #: (direction, {knob: pre-move value}, pre-move rate) awaiting
        #: its post-cooldown throughput verdict
        self._pending_eval: "tuple | None" = None
        #: direction -> ticks it stays blocked after a revert
        self._tabu: "dict[int, int]" = {}
        self.decision_count = 0
        self._thread: "threading.Thread | None" = None
        self._stop = threading.Event()

    # -- knob registry -------------------------------------------------------
    def register(self, knob: Knob) -> Knob:
        """Add (or replace) a knob; exports its current value on the
        ``sparkdl_autotune_knob`` gauge immediately, pinned or not."""
        with self._lock:
            self._knobs[knob.name] = knob
        _metrics()[1].set(float(knob.get()), knob=knob.name)
        return knob

    def register_all(self, knobs: Iterable[Knob]) -> "list[Knob]":
        return [self.register(k) for k in knobs]

    def unregister(self, name: str, knob: "Knob | None" = None) -> None:
        """Remove a knob by name. Pass the :class:`Knob` object to make
        the removal identity-checked: if another stream re-registered
        the same name in the meantime, ITS live knob is left in place
        (a closing pipeline must never deregister a successor's)."""
        with self._lock:
            if knob is not None and self._knobs.get(name) is not knob:
                return
            self._knobs.pop(name, None)

    @property
    def knobs(self) -> "dict[str, Knob]":
        with self._lock:
            return dict(self._knobs)

    # -- the control loop ----------------------------------------------------
    def tick(self) -> int:
        """Take one sample and maybe move knobs; returns the number of
        knob moves applied this tick (reverts included)."""
        now = self._clock()
        sig = self._signals()
        starve, blocked = float(sig[0]), float(sig[1])
        items = float(sig[2]) if len(sig) > 2 else None
        _metrics()[2].inc()
        last = self._last_sample
        self._last_sample = (now, starve, blocked, items)
        for d in list(self._tabu):
            self._tabu[d] -= 1
            if self._tabu[d] <= 0:
                del self._tabu[d]
        if last is None:
            return 0
        dt = now - last[0]
        if dt <= 0:
            return 0
        starve_share = max(0.0, starve - last[1]) / dt
        blocked_share = max(0.0, blocked - last[2]) / dt
        rate = (max(0.0, items - last[3]) / dt
                if items is not None and last[3] is not None else None)

        if self._cooldown > 0:
            # a fresh move is still taking effect; don't let the
            # transient it causes count toward the next decision
            self._cooldown -= 1
            self._streak = 0
            self._streak_dir = 0
            return 0
        if self._pending_eval is not None:
            # the throughput verdict on the last move: a drop beyond
            # tolerance means the bottleneck shares pointed the wrong
            # way for THIS workload — undo it and stop retrying
            d, before, rate0 = self._pending_eval
            self._pending_eval = None
            if (rate is not None and rate0 is not None and rate0 > 0
                    and rate < (1.0 - self.revert_tolerance) * rate0):
                return self._revert(d, before)

        if starve_share >= self.starve_hi and starve_share >= blocked_share:
            direction = 1  # feed starved: grow the producer side
        elif blocked_share >= self.blocked_hi and starve_share < self.starve_lo:
            direction = -1  # consumer-bound: shrink back
        else:
            direction = 0

        if direction == 0 or direction in self._tabu:
            self._streak_dir = 0
            self._streak = 0
            return 0
        if direction != self._streak_dir:
            self._streak_dir = direction
            self._streak = 1
        else:
            self._streak += 1
        if self._streak < self.hysteresis:
            return 0
        # decision: move every unpinned knob one bounded step
        moved = self._apply(direction, rate)
        self._streak = 0
        self._streak_dir = 0
        if moved:
            self._cooldown = self.cooldown_ticks
        return moved

    def _apply(self, direction: int, rate: "float | None") -> int:
        decisions_m, gauge_m, _ = _metrics()
        moved = 0
        before: "dict[str, int]" = {}
        t0 = time.monotonic()
        for knob in self.knobs.values():
            if knob.pinned:
                continue
            d = -direction if knob.inverted else direction
            cur = int(knob.get())
            want = _pow2_step(cur, d, knob.lo, knob.hi)
            if want == cur:
                continue
            knob.set(want)
            new = int(knob.get())  # a knob may clamp (policy ceilings):
            if new == cur:         # only a REAL change is a decision
                continue
            before[knob.name] = cur
            moved += 1
            self.decision_count += 1
            direction_s = "grow" if new > cur else "shrink"
            decisions_m.inc(knob=knob.name, direction=direction_s)
            gauge_m.set(float(new), knob=knob.name)
            # the decision HISTORY is what postmortems need (tf.data's
            # AUTOTUNE lesson): the knob value alone hides the causality
            flight.record_event(
                "autotune.decision", knob=knob.name,
                direction=direction_s, value=new, previous=cur,
            )
        if moved:
            self._pending_eval = (direction, before, rate)
            tracing.record_span(
                "autotune.decision", t0, time.monotonic(),
                direction="grow" if direction > 0 else "shrink",
                knobs_moved=moved,
            )
        return moved

    def _revert(self, direction: int, before: "dict[str, int]") -> int:
        decisions_m, gauge_m, _ = _metrics()
        knobs = self.knobs
        moved = 0
        t0 = time.monotonic()
        for name, old in before.items():
            knob = knobs.get(name)
            if knob is None:
                continue
            knob.set(old)
            moved += 1
            self.decision_count += 1
            decisions_m.inc(knob=name, direction="revert")
            gauge_m.set(float(int(knob.get())), knob=name)
            flight.record_event(
                "autotune.decision", knob=name, direction="revert",
                value=old,
            )
        self._tabu[direction] = self.tabu_ticks
        self._cooldown = self.cooldown_ticks
        if moved:
            tracing.record_span(
                "autotune.decision", t0, time.monotonic(),
                direction="revert", knobs_moved=moved,
            )
        return moved

    # -- cadence thread ------------------------------------------------------
    def start(self) -> "AutoTuner":
        """Run :meth:`tick` every ``interval_s`` on a daemon thread.
        Idempotent; :meth:`stop` joins the thread."""
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return self
            # each cadence thread owns a FRESH stop event (captured by
            # argument, never re-read): a start() racing a stop() can
            # therefore never resurrect the old thread — the old event
            # stays set and that thread exits at its next wake, while
            # the new thread waits on the new event (sparkdl-lint
            # lock-discipline follow-up: re-using one cleared event
            # here used to leave TWO live tick loops)
            stop = self._stop = threading.Event()
            self._thread = threading.Thread(
                target=self._loop, args=(stop,),
                name="sparkdl-autotune", daemon=True,
            )
            self._thread.start()
        return self

    def _loop(self, stop: threading.Event) -> None:
        import logging

        log = logging.getLogger(__name__)
        errors_m = registry().counter(
            "sparkdl_autotune_tick_errors_total",
            "autotuner samples that raised (knob raced its stream "
            "closing, or a broken signal reader)")
        logged = False
        while not stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:
                # usually a knob's set() racing its stream closing —
                # survivable — but a PERSISTENTLY failing reader would
                # otherwise be indistinguishable from 'correctly idle':
                # count every failure, log the first with traceback
                errors_m.inc()
                if not logged:
                    logged = True
                    log.warning("autotuner tick failed (continuing; "
                                "counted in sparkdl_autotune_tick_"
                                "errors_total)", exc_info=True)
                continue

    def stop(self) -> None:
        # the stop signal AND the thread-handle claim happen under the
        # same lock start() uses (sparkdl-lint lock-discipline): a stop
        # racing a start can no longer clobber the fresh handle with
        # None, and since every thread owns its event (start swaps in a
        # fresh one under this lock), setting the current event can
        # only ever stop the current thread. The join stays OUTSIDE the
        # lock — tick() takes it, so joining while holding it would
        # deadlock.
        with self._lock:
            self._stop.set()
            t = self._thread
            self._thread = None
        if t is not None and t.is_alive():
            t.join(timeout=2.0)

    def __enter__(self) -> "AutoTuner":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


_DEFAULT_TUNER: "AutoTuner | None" = None
_DEFAULT_LOCK = threading.Lock()


def default_tuner() -> AutoTuner:
    """The process-wide tuner instance (not started until a consumer
    with autotuning enabled starts it)."""
    global _DEFAULT_TUNER
    with _DEFAULT_LOCK:
        if _DEFAULT_TUNER is None:
            _DEFAULT_TUNER = AutoTuner()
        return _DEFAULT_TUNER


def autotune_telemetry() -> dict:
    """Decision count + steady-state knob values, straight off the
    registry (the same series ``/metrics`` scrapes) — the
    ``"autotune"`` field the benches embed in their JSON line. The knob
    gauge keeps its last value after streams close, so this reads the
    steady state a run converged to."""
    reg = registry()
    dec_fam = reg.get("sparkdl_autotune_decisions_total")
    decisions = (sum(dec_fam.labelled_values("knob").values())
                 if dec_fam else 0)
    knob_fam = reg.get("sparkdl_autotune_knob")
    knobs = ({k: int(v) for k, v in
              knob_fam.labelled_values("knob").items()}
             if knob_fam else {})
    return {"decisions": int(decisions), "knobs": knobs}


def autotune_enabled(flag: "bool | None" = None) -> bool:
    """Resolve a consumer's ``autotune`` setting: an explicit bool wins;
    None defers to ``SPARKDL_TPU_AUTOTUNE`` (default off — a background
    control thread must be asked for)."""
    if flag is not None:
        return flag
    return os.environ.get("SPARKDL_TPU_AUTOTUNE", "").lower() in (
        "1", "true", "yes", "on")
