from sparkdl_tpu.param.shared_params import (
    Estimator,
    HasBatchSize,
    HasInputCol,
    HasLabelCol,
    HasOutputCol,
    Param,
    Params,
    Pipeline,
    PipelineModel,
    Transformer,
)
from sparkdl_tpu.param.converters import SparkDLTypeConverters

__all__ = [
    "Estimator",
    "HasBatchSize",
    "HasInputCol",
    "HasLabelCol",
    "HasOutputCol",
    "Param",
    "Params",
    "Pipeline",
    "PipelineModel",
    "SparkDLTypeConverters",
    "Transformer",
]
