"""Spark-ML-compatible Param system.

Parity with pyspark.ml.param as used by the reference (SURVEY.md 2.19, [U:
python/sparkdl/param/shared_params.py]): typed ``Param`` descriptors on
``Params`` objects with defaults, setters, ``extractParamMap`` and
``copy(extra)`` semantics — so reference-style code
(``KerasTransformer(inputCol=..., modelFile=...)``,
``est.fit(df, paramMaps)``) works verbatim without a pyspark dependency.
When pyspark is present the classes interoperate (paramMaps keyed by either
implementation's Param objects by name).
"""

from __future__ import annotations

import copy as _copy
from typing import Any, Callable


class Param:
    """A named, documented parameter with an optional type converter."""

    def __init__(self, parent: "Params | type | None", name: str, doc: str,
                 typeConverter: Callable[[Any], Any] | None = None):
        self.parent = parent
        self.name = name
        self.doc = doc
        self.typeConverter = typeConverter or (lambda v: v)

    def _copy_for(self, parent: "Params") -> "Param":
        p = Param(parent, self.name, self.doc, self.typeConverter)
        return p

    def __repr__(self) -> str:
        owner = type(self.parent).__name__ if isinstance(self.parent, Params) else self.parent
        return f"Param({owner}.{self.name})"

    def __hash__(self):
        return hash(self.name)

    def __eq__(self, other):
        return isinstance(other, Param) and other.name == self.name


class Params:
    """Base class: anything with Params (Transformers, Estimators, Models)."""

    def __init__(self):
        self._paramMap: dict[Param, Any] = {}
        self._defaultParamMap: dict[Param, Any] = {}
        # Rebind class-level Param descriptors to this instance so that
        # `self.inputCol is type(self).inputCol` comparisons by name work.
        for name in dir(type(self)):
            attr = getattr(type(self), name, None)
            if isinstance(attr, Param):
                setattr(self, name, attr._copy_for(self))

    # -- declaration helpers ---------------------------------------------
    @property
    def params(self) -> list[Param]:
        # Instance-rebound Params live in __dict__ (see __init__); scanning
        # only __dict__ avoids re-entering properties like this one.
        found = {
            v.name: v for v in self.__dict__.values() if isinstance(v, Param)
        }
        return sorted(found.values(), key=lambda p: p.name)

    def _resolveParam(self, param: "Param | str") -> Param:
        if isinstance(param, str):
            for p in self.params:
                if p.name == param:
                    return p
            raise KeyError(f"no param named {param!r} on {type(self).__name__}")
        # cross-instance / cross-implementation: match by name
        for p in self.params:
            if p.name == param.name:
                return p
        raise KeyError(f"param {param} does not belong to {type(self).__name__}")

    # -- get/set ----------------------------------------------------------
    def _set(self, **kwargs) -> "Params":
        for name, value in kwargs.items():
            if value is None:
                continue
            p = self._resolveParam(name)
            self._paramMap[p] = p.typeConverter(value)
        return self

    def _setDefault(self, **kwargs) -> "Params":
        for name, value in kwargs.items():
            p = self._resolveParam(name)
            self._defaultParamMap[p] = value
        return self

    def set(self, param: "Param | str", value) -> "Params":
        p = self._resolveParam(param)
        self._paramMap[p] = p.typeConverter(value)
        return self

    def isSet(self, param: "Param | str") -> bool:
        return self._resolveParam(param) in self._paramMap

    def isDefined(self, param: "Param | str") -> bool:
        p = self._resolveParam(param)
        return p in self._paramMap or p in self._defaultParamMap

    def hasParam(self, name: str) -> bool:
        try:
            self._resolveParam(name)
            return True
        except KeyError:
            return False

    def getOrDefault(self, param: "Param | str"):
        p = self._resolveParam(param)
        if p in self._paramMap:
            return self._paramMap[p]
        if p in self._defaultParamMap:
            return self._defaultParamMap[p]
        raise KeyError(f"param {p.name} is not set and has no default")

    def getParam(self, name: str) -> Param:
        return self._resolveParam(name)

    def extractParamMap(self, extra: dict | None = None) -> dict[Param, Any]:
        m = dict(self._defaultParamMap)
        m.update(self._paramMap)
        if extra:
            for k, v in extra.items():
                m[self._resolveParam(k)] = v
        return m

    def copy(self, extra: dict | None = None) -> "Params":
        that = _copy.deepcopy(self)
        if extra:
            for k, v in extra.items():
                p = that._resolveParam(k)
                that._paramMap[p] = p.typeConverter(v)
        return that

    def clear(self, param: "Param | str") -> "Params":
        self._paramMap.pop(self._resolveParam(param), None)
        return self

    def explainParams(self) -> str:
        lines = []
        for p in self.params:
            cur = "undefined"
            if self.isDefined(p):
                cur = repr(self.getOrDefault(p))
            lines.append(f"{p.name}: {p.doc} (current: {cur})")
        return "\n".join(lines)

    def _kwargs_from_params(self, kwargs: dict) -> dict:
        return {k: v for k, v in kwargs.items() if v is not None}


# -- shared column params (parity with pyspark.ml.param.shared) -----------

class HasInputCol(Params):
    inputCol = Param(None, "inputCol", "input column name")

    def setInputCol(self, value: str):
        return self._set(inputCol=value)

    def getInputCol(self) -> str:
        return self.getOrDefault("inputCol")


class HasOutputCol(Params):
    outputCol = Param(None, "outputCol", "output column name")

    def setOutputCol(self, value: str):
        return self._set(outputCol=value)

    def getOutputCol(self) -> str:
        return self.getOrDefault("outputCol")


class HasLabelCol(Params):
    labelCol = Param(None, "labelCol", "label column name")

    def setLabelCol(self, value: str):
        return self._set(labelCol=value)

    def getLabelCol(self) -> str:
        return self.getOrDefault("labelCol")


class HasBatchSize(Params):
    batchSize = Param(None, "batchSize", "rows per device batch")

    def setBatchSize(self, value: int):
        return self._set(batchSize=int(value))

    def getBatchSize(self) -> int:
        return self.getOrDefault("batchSize")


class Transformer(Params):
    """Spark-ML Transformer shape: ``transform(df) -> df``."""

    def transform(self, dataset, params: dict | None = None):
        if params:
            return self.copy(params).transform(dataset)
        return self._transform(dataset)

    def _transform(self, dataset):  # pragma: no cover - abstract
        raise NotImplementedError


class Estimator(Params):
    """Spark-ML Estimator shape: ``fit(df[, params]) -> Model(s)``."""

    def fit(self, dataset, params: "dict | list[dict] | None" = None):
        if isinstance(params, (list, tuple)):
            return self.fitMultiple(dataset, list(params))
        if params:
            return self.copy(params)._fit(dataset)
        return self._fit(dataset)

    def fitMultiple(self, dataset, paramMaps: list[dict]):
        """Default: sequential fits; estimators override to parallelize."""
        return [self.copy(pm)._fit(dataset) for pm in paramMaps]

    def _fit(self, dataset):  # pragma: no cover - abstract
        raise NotImplementedError


class Pipeline(Estimator):
    """Minimal Spark-ML Pipeline: chain of Transformers/Estimators."""

    def __init__(self, stages: list | None = None):
        super().__init__()
        self._stages = list(stages or [])

    def setStages(self, stages: list) -> "Pipeline":
        self._stages = list(stages)
        return self

    def getStages(self) -> list:
        return self._stages

    def _fit(self, dataset):
        def is_estimator(s):
            return isinstance(s, Estimator) or (
                hasattr(s, "fit") and not isinstance(s, Transformer)
            )

        last_est = max(
            (i for i, s in enumerate(self._stages) if is_estimator(s)),
            default=-1,
        )
        transformers = []
        df = dataset
        for i, stage in enumerate(self._stages):
            if is_estimator(stage):
                model = stage.fit(df)
            else:
                model = stage
            transformers.append(model)
            # Only materialize intermediate data while a later stage still
            # needs it for fitting (pyspark.ml.Pipeline semantics).
            if i < last_est:
                df = model.transform(df)
        return PipelineModel(transformers)


class PipelineModel(Transformer):
    def __init__(self, stages: list):
        super().__init__()
        self._stages = list(stages)

    def _transform(self, dataset):
        df = dataset
        for stage in self._stages:
            df = stage.transform(df)
        return df
