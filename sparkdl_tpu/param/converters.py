"""Type converters for Params.

Parity with the reference's SparkDLTypeConverters (SURVEY.md 2.19, [U:
python/sparkdl/param/converters.py]): validating conversion of user-supplied
values — model files, column name maps, channel orders — with clear errors
at set-time rather than failures deep inside transform().
"""

from __future__ import annotations

import os
from typing import Any


class SparkDLTypeConverters:
    @staticmethod
    def toString(value: Any) -> str:
        if isinstance(value, str):
            return value
        raise TypeError(f"expected str, got {type(value).__name__}")

    @staticmethod
    def toInt(value: Any) -> int:
        if isinstance(value, bool):
            raise TypeError("expected int, got bool")
        if isinstance(value, int):
            return value
        if isinstance(value, float) and value.is_integer():
            return int(value)
        raise TypeError(f"expected int, got {value!r}")

    @staticmethod
    def toFloat(value: Any) -> float:
        if isinstance(value, (int, float)) and not isinstance(value, bool):
            return float(value)
        raise TypeError(f"expected float, got {value!r}")

    @staticmethod
    def toBoolean(value: Any) -> bool:
        if isinstance(value, bool):
            return value
        raise TypeError(f"expected bool, got {value!r}")

    @staticmethod
    def toExistingFilePath(value: Any) -> str:
        path = SparkDLTypeConverters.toString(value)
        if not os.path.isfile(path):
            raise ValueError(f"model file does not exist: {path}")
        return path

    @staticmethod
    def toColumnToTensorNameMap(value: Any) -> dict[str, str]:
        return SparkDLTypeConverters._toStrStrMap(value, "column -> tensor name")

    @staticmethod
    def toTensorNameToColumnMap(value: Any) -> dict[str, str]:
        return SparkDLTypeConverters._toStrStrMap(value, "tensor name -> column")

    @staticmethod
    def _toStrStrMap(value: Any, what: str) -> dict[str, str]:
        if not isinstance(value, dict) or not value:
            raise TypeError(f"expected a non-empty dict for {what}, got {value!r}")
        out = {}
        for k, v in value.items():
            if not isinstance(k, str) or not isinstance(v, str):
                raise TypeError(f"{what} entries must be str->str, got {k!r}: {v!r}")
            out[k] = v
        return out

    @staticmethod
    def toTFInputGraph(value: Any):
        from sparkdl_tpu.graph.input import TFInputGraph

        if isinstance(value, TFInputGraph):
            return value
        raise TypeError(
            f"expected a TFInputGraph (see TFInputGraph.from*), got "
            f"{type(value).__name__}"
        )

    @staticmethod
    def toChannelOrder(value: Any) -> str:
        v = SparkDLTypeConverters.toString(value)
        if v not in ("RGB", "BGR", "L"):
            raise ValueError(f"channel order must be RGB, BGR or L, got {v!r}")
        return v

    @staticmethod
    def supportedNameConverter(supported: list[str]):
        def convert(value: Any) -> str:
            v = SparkDLTypeConverters.toString(value)
            if v not in supported:
                raise ValueError(f"{v!r} not in supported set {sorted(supported)}")
            return v

        return convert

    @staticmethod
    def toKerasLoss(value: Any) -> str:
        v = SparkDLTypeConverters.toString(value)
        return v

    @staticmethod
    def toKerasOptimizer(value: Any) -> str:
        v = SparkDLTypeConverters.toString(value)
        return v
