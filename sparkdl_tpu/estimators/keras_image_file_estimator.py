"""KerasImageFileEstimator — transfer-learning / HPO over image URIs.

Reference parity (SURVEY.md 2.12/3.3, [U: python/sparkdl/estimators/
keras_image_file_estimator.py]): ``fit(df, paramMaps)`` materializes (X, y)
once via the user's ``imageLoader``, then trains one Keras model per param
map (the reference fans these out across Spark tasks; here they run through
a worker pool on the driver host — single-model training is *not* what this
component distributes, in either implementation). Each fit saves a tuned
model and returns it wrapped as a ``KerasImageFileTransformer``.

Keras 3 on the jax backend means each ``model.fit`` is itself jit-compiled
and runs on the TPU/devices available to this process; real multi-host
data-parallel training belongs to TPURunner (SURVEY.md 2.13 parity).
"""

from __future__ import annotations

import os
import tempfile
from typing import Any, Sequence

import numpy as np

from sparkdl_tpu.param import (
    Estimator,
    HasBatchSize,
    HasInputCol,
    HasLabelCol,
    HasOutputCol,
    Param,
    SparkDLTypeConverters,
)
from sparkdl_tpu.transformers.keras_image import CanLoadImage, KerasImageFileTransformer


class KerasImageFileEstimator(
    Estimator, CanLoadImage, HasInputCol, HasOutputCol, HasLabelCol, HasBatchSize
):
    modelFile = Param(
        None, "modelFile", "path to the Keras model to start training from",
        SparkDLTypeConverters.toExistingFilePath,
    )
    kerasOptimizer = Param(
        None, "kerasOptimizer", "Keras optimizer name (e.g. 'adam')",
        SparkDLTypeConverters.toKerasOptimizer,
    )
    kerasLoss = Param(
        None, "kerasLoss", "Keras loss name (e.g. 'categorical_crossentropy')",
        SparkDLTypeConverters.toKerasLoss,
    )
    kerasFitParams = Param(
        None, "kerasFitParams", "kwargs dict forwarded to keras Model.fit",
    )

    def __init__(self, inputCol=None, outputCol=None, labelCol=None,
                 modelFile=None, imageLoader=None, kerasOptimizer=None,
                 kerasLoss=None, kerasFitParams=None, batchSize=None):
        super().__init__()
        self._setDefault(
            kerasOptimizer="adam", kerasFitParams={"verbose": 0}, batchSize=32
        )
        self._set(inputCol=inputCol, outputCol=outputCol, labelCol=labelCol,
                  modelFile=modelFile, imageLoader=imageLoader,
                  kerasOptimizer=kerasOptimizer, kerasLoss=kerasLoss,
                  kerasFitParams=kerasFitParams, batchSize=batchSize)

    # -- data materialization (reference: imageLoader UDF -> numpy) --------
    def _collect_xy(self, dataset) -> tuple[np.ndarray, "np.ndarray | None"]:
        input_col = self.getInputCol()
        label_col = self.getOrDefault("labelCol") if self.isDefined("labelCol") else None
        uris, labels = [], []
        rows = dataset.collect() if hasattr(dataset, "collect") else list(dataset)
        for r in rows:
            uris.append(r[input_col])
            if label_col is not None:
                labels.append(r[label_col])
        x = np.stack([self._load_one(u) for u in uris])
        y = np.asarray(labels, dtype=np.float32) if labels else None
        return x, y

    def _load_one(self, uri: str) -> np.ndarray:
        arr = np.asarray(self.loadImage(uri), dtype=np.float32)
        if arr.ndim == 4 and arr.shape[0] == 1:
            arr = arr[0]
        return arr

    # -- fitting -----------------------------------------------------------
    def _fit(self, dataset) -> KerasImageFileTransformer:
        return self.fitMultiple(dataset, [{}])[0]

    def fitMultiple(self, dataset, paramMaps: Sequence[dict]) -> list:
        """One tuned model per param map, trained over the shared (X, y)."""
        x, y = self._collect_xy(dataset)
        if y is None:
            raise ValueError("labelCol must be set (and present) to fit")
        return [self._fit_one(pm, x, y) for pm in paramMaps]

    def _fit_one(self, param_map: dict, x: np.ndarray, y: np.ndarray):
        est: KerasImageFileEstimator = self.copy(param_map) if param_map else self
        import keras

        model = keras.models.load_model(est.getOrDefault("modelFile"), compile=False)
        model.compile(
            optimizer=est.getOrDefault("kerasOptimizer"),
            loss=est.getOrDefault("kerasLoss"),
        )
        fit_params: dict[str, Any] = dict(est.getOrDefault("kerasFitParams"))
        fit_params.setdefault("verbose", 0)
        model.fit(x, y, batch_size=est.getBatchSize(), **fit_params)

        fd, path = tempfile.mkstemp(suffix=".keras", prefix="sparkdl_tuned_")
        os.close(fd)
        model.save(path)
        return KerasImageFileTransformer(
            inputCol=est.getInputCol(),
            outputCol=est.getOutputCol(),
            modelFile=path,
            imageLoader=est.getImageLoader(),
            batchSize=est.getBatchSize(),
        )
