from sparkdl_tpu.estimators.keras_image_file_estimator import KerasImageFileEstimator

__all__ = ["KerasImageFileEstimator"]
