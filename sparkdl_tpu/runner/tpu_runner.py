"""TPURunner — HorovodRunner-parity distributed training runner.

Reference parity (SURVEY.md 2.13/3.4, [U: DBR sparkdl.horovod
HorovodRunner]): ``TPURunner(np).run(main_fn, **kwargs)``.

* ``np < 0`` — debug mode: ``|np|`` local processes on this host (the
  reference's driver-local mode), CPU devices by default.
* ``np > 0`` — cluster mode: one Spark barrier task per TPU host.

Inside ``main_fn`` there is no hvd.init()/DistributedOptimizer: the process
is already a member of the global JAX runtime (``jax.process_index()``,
``jax.device_count()``), and gradient sync is the ``psum`` XLA emits from
pjit/shard_map sharding annotations — see sparkdl_tpu.parallel for the
train-step builders. ``HorovodRunner`` is exported as an alias so reference
call sites run unchanged.
"""

from __future__ import annotations

from typing import Any, Callable

from sparkdl_tpu.runner.backends import LocalProcessBackend, SparkBarrierBackend

_VERBOSITIES = ("all", "none")


class TPURunner:
    """Launch a function on every process of a TPU job and return rank 0's
    result to the driver."""

    def __init__(self, np: int, driver_log_verbosity: str = "all",
                 backend=None, devices_per_process: int = 1,
                 local_platform: "str | None" = "cpu",
                 timeout_s: float = 600.0,
                 metrics_summary: bool = False,
                 straggler_grace_s: "float | None" = None):
        if np == 0:
            raise ValueError("np must be a non-zero integer")
        if driver_log_verbosity not in _VERBOSITIES:
            raise ValueError(
                f"driver_log_verbosity must be one of {_VERBOSITIES}"
            )
        self.np = int(np)
        self.driver_log_verbosity = driver_log_verbosity
        self.metrics_summary = metrics_summary
        self._backend = backend
        self._devices_per_process = devices_per_process
        self._local_platform = local_platform
        self._timeout_s = timeout_s
        #: rank watchdog grace (local mode): once the first rank exits,
        #: survivors past this window are torn down as hung instead of
        #: blocking peers (e.g. in the collective metrics rollup) until
        #: timeout_s. None = disabled.
        self._straggler_grace_s = straggler_grace_s

    def run(self, main: Callable, **kwargs: Any) -> Any:
        """Run ``main(**kwargs)`` on all ranks; returns rank 0's result.

        With ``metrics_summary=True`` every rank's metrics registry is
        aggregated across hosts after its ``main`` returns (mean/min/max
        per series via ``aggregate_across_hosts``) and rank 0 logs the
        rollup under the ``sparkdl_tpu.metrics`` logger. The rollup is a
        collective: if one rank's ``main`` raises, surviving ranks block
        in it until the backend tears the job down (LocalProcessBackend
        kills peers on first failure; a Spark barrier stage aborts), so
        the failure still surfaces — just on the backend's timeout path.
        """
        if not callable(main):
            raise TypeError("main must be callable")
        backend = self._backend or self._default_backend()
        fn = _with_metrics_summary(main) if self.metrics_summary else main
        return backend.run(
            abs(self.np), fn, kwargs, verbosity=self.driver_log_verbosity
        )

    def _default_backend(self):
        if self.np < 0:
            return LocalProcessBackend(
                devices_per_process=self._devices_per_process,
                platform=self._local_platform,
                timeout_s=self._timeout_s,
                straggler_grace_s=self._straggler_grace_s,
            )
        try:
            return SparkBarrierBackend()
        except Exception as e:
            raise RuntimeError(
                f"np={self.np} requires a cluster backend: {e}. Use a "
                "negative np for local debug mode, or pass backend= "
                "explicitly."
            ) from e


def _with_metrics_summary(main: Callable) -> Callable:
    """Wrap ``main`` so every rank joins the post-run metrics rollup.

    The wrapper runs on the EXECUTOR (it rides the cloudpickled payload):
    after the user fn returns, all ranks call
    :func:`sparkdl_tpu.observability.snapshot_across_hosts` — a collective
    over the flattened registry, which assumes SPMD instrumentation (every
    rank records the same metric names, the usual case for a training
    fn) — and rank 0 logs the mean/min/max rollup as one JSON line.
    """

    def main_with_metrics(**kwargs):
        result = main(**kwargs)
        import json
        import logging

        import jax

        from sparkdl_tpu.observability import snapshot_across_hosts

        try:
            agg = snapshot_across_hosts()
            if agg and jax.process_index() == 0:
                logging.getLogger("sparkdl_tpu.metrics").info(
                    "all-host metrics %s", json.dumps(agg, sort_keys=True)
                )
        except Exception:  # observability must never fail the job
            logging.getLogger("sparkdl_tpu.metrics").warning(
                "cross-host metrics rollup failed", exc_info=True
            )
        return result

    return main_with_metrics


#: Drop-in alias: reference code `HorovodRunner(np=...).run(fn)` runs as-is.
HorovodRunner = TPURunner
