"""TPURunner — HorovodRunner-parity distributed training runner.

Reference parity (SURVEY.md 2.13/3.4, [U: DBR sparkdl.horovod
HorovodRunner]): ``TPURunner(np).run(main_fn, **kwargs)``.

* ``np < 0`` — debug mode: ``|np|`` local processes on this host (the
  reference's driver-local mode), CPU devices by default.
* ``np > 0`` — cluster mode: one Spark barrier task per TPU host.

Inside ``main_fn`` there is no hvd.init()/DistributedOptimizer: the process
is already a member of the global JAX runtime (``jax.process_index()``,
``jax.device_count()``), and gradient sync is the ``psum`` XLA emits from
pjit/shard_map sharding annotations — see sparkdl_tpu.parallel for the
train-step builders. ``HorovodRunner`` is exported as an alias so reference
call sites run unchanged.
"""

from __future__ import annotations

from typing import Any, Callable

from sparkdl_tpu.runner.backends import LocalProcessBackend, SparkBarrierBackend

_VERBOSITIES = ("all", "none")


class TPURunner:
    """Launch a function on every process of a TPU job and return rank 0's
    result to the driver."""

    def __init__(self, np: int, driver_log_verbosity: str = "all",
                 backend=None, devices_per_process: int = 1,
                 local_platform: "str | None" = "cpu",
                 timeout_s: float = 600.0):
        if np == 0:
            raise ValueError("np must be a non-zero integer")
        if driver_log_verbosity not in _VERBOSITIES:
            raise ValueError(
                f"driver_log_verbosity must be one of {_VERBOSITIES}"
            )
        self.np = int(np)
        self.driver_log_verbosity = driver_log_verbosity
        self._backend = backend
        self._devices_per_process = devices_per_process
        self._local_platform = local_platform
        self._timeout_s = timeout_s

    def run(self, main: Callable, **kwargs: Any) -> Any:
        """Run ``main(**kwargs)`` on all ranks; returns rank 0's result."""
        if not callable(main):
            raise TypeError("main must be callable")
        backend = self._backend or self._default_backend()
        return backend.run(
            abs(self.np), main, kwargs, verbosity=self.driver_log_verbosity
        )

    def _default_backend(self):
        if self.np < 0:
            return LocalProcessBackend(
                devices_per_process=self._devices_per_process,
                platform=self._local_platform,
                timeout_s=self._timeout_s,
            )
        try:
            return SparkBarrierBackend()
        except Exception as e:
            raise RuntimeError(
                f"np={self.np} requires a cluster backend: {e}. Use a "
                "negative np for local debug mode, or pass backend= "
                "explicitly."
            ) from e


#: Drop-in alias: reference code `HorovodRunner(np=...).run(fn)` runs as-is.
HorovodRunner = TPURunner
