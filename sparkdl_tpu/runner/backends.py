"""Cluster backends for TPURunner: local processes and Spark barrier jobs.

Reference parity (SURVEY.md 2.13/3.4): HorovodRunner's two regimes —
``np < 0`` local debug processes, ``np > 0`` Spark barrier tasks with an
MPI rendezvous — map here to :class:`LocalProcessBackend` (subprocesses on
this host) and :class:`SparkBarrierBackend` (one barrier task per TPU host,
rendezvous via ``BarrierTaskContext.allGather``). Both end in
``jax.distributed.initialize``: in-step gradient comm is XLA collectives
over ICI/DCN compiled into the program, so there is no user-space ring to
bootstrap — only the coordinator address exchange.
"""

from __future__ import annotations

import logging
import os
import pickle
import shutil
import socket
import subprocess
import sys
import tempfile
import threading
from typing import Any, Callable

logger = logging.getLogger(__name__)


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def virtual_cpu_overrides(n_devices: int, existing_flags: str = "") -> dict:
    """Env overrides forcing an ``n_devices``-way virtual CPU platform.

    The single source of truth for the "fake mesh" env contract used by the
    test conftest, LocalProcessBackend children, and the graft-entry
    dry-run re-exec: ``JAX_PLATFORMS=cpu`` plus
    ``--xla_force_host_platform_device_count`` (any existing count flag in
    ``existing_flags`` is replaced, not duplicated). Overrides must be in
    place before the target process initializes a jax backend.
    """
    flags = [
        f
        for f in existing_flags.split()
        if not f.startswith("--xla_force_host_platform_device_count")
    ]
    flags.append(f"--xla_force_host_platform_device_count={n_devices}")
    return {"JAX_PLATFORMS": "cpu", "XLA_FLAGS": " ".join(flags)}


def tpu_chip_pin_overrides(chip: int) -> dict:
    """Env overrides pinning a child process to ONE local TPU chip.

    The companion of :func:`virtual_cpu_overrides` for real hardware:
    concurrent single-host child interpreters (process trial runners,
    per-chip workers) must each see a disjoint chip, or they deadlock on
    the libtpu lock. Must be in the child env before it imports jax.
    """
    return {
        "TPU_VISIBLE_DEVICES": str(chip),
        "TPU_CHIPS_PER_PROCESS_BOUNDS": "1,1,1",
        "TPU_PROCESS_BOUNDS": "1,1,1",
    }


def local_pinnable_chips() -> "list[int]":
    """Chip indices available for per-process pinning on this host.

    MUST NOT touch jax: initializing a backend here would make the
    DRIVER process acquire every chip and starve the very children the
    pins are for. Detection is chip-granular (TPU_VISIBLE_DEVICES takes
    chip ids, and jax device counts are CORES — 2x the chips on some
    generations): an existing TPU_VISIBLE_DEVICES restriction is
    respected, else the host's /dev/accel* entries (one per chip on TPU
    VMs) are counted. Empty on chipless/CPU hosts — fresh interpreters
    don't contend there, so no pinning is needed.
    """
    import glob
    import re

    env = os.environ.get("TPU_VISIBLE_DEVICES")
    if env is not None:
        try:
            return [int(x) for x in env.split(",") if x.strip() != ""]
        except ValueError:
            logger.warning(
                "unparseable TPU_VISIBLE_DEVICES=%r; falling back to "
                "device-file chip detection", env,
            )
    # /dev/accel<N>: N IS the chip index
    chips = sorted(
        int(m.group(1))
        for m in (re.fullmatch(r"accel(\d+)", os.path.basename(p))
                  for p in glob.glob("/dev/accel*"))
        if m
    )
    if chips:
        return chips
    # vfio-exposed hosts: /dev/vfio/<N> are IOMMU GROUP numbers, not
    # chip ids — TPU_VISIBLE_DEVICES wants logical chip indices, so
    # return 0..count-1 and only the numeric entries (skips the
    # /dev/vfio/vfio control node). vfio entries alone are NOT a TPU
    # signal — GPUs and NICs passthrough the same way — so demand a
    # second, independent one (libtpu on the path, or a Google PCI
    # device) before pinning; on mismatch fall back to unpinned rather
    # than pin children to nonexistent chip indices.
    n = sum(
        1 for p in glob.glob("/dev/vfio/*")
        if re.fullmatch(r"\d+", os.path.basename(p))
    )
    if n and not _vfio_is_tpu():
        logger.warning(
            "%d /dev/vfio entries but no TPU signal (no libtpu, no Google "
            "PCI vendor id): not pinning chips — trials run unpinned", n,
        )
        return []
    return list(range(n))


#: Google's PCI vendor id; TPU boards enumerate under it on vfio hosts.
_GOOGLE_PCI_VENDOR = "0x1ae0"


def _vfio_is_tpu() -> bool:
    """Second TPU signal for the vfio fallback (jax-free, like the caller):
    libtpu importable, or any PCI device with Google's vendor id."""
    import glob
    import importlib.util

    try:
        if importlib.util.find_spec("libtpu") is not None:
            return True
    except (ImportError, ValueError):
        pass
    for p in glob.glob("/sys/bus/pci/devices/*/vendor"):
        try:
            with open(p) as f:
                if f.read().strip().lower() == _GOOGLE_PCI_VENDOR:
                    return True
        except OSError:
            continue
    return False


class LocalProcessBackend:
    """Run n ranks as subprocesses of this host (HorovodRunner np<0 mode).

    Each rank is a fresh interpreter (env must precede jax import). By
    default ranks run on CPU with ``devices_per_process`` fake devices each,
    so multi-process collective code is debuggable on one machine with (or
    without) a single TPU chip.
    """

    def __init__(self, devices_per_process: int = 1, platform: "str | None" = "cpu",
                 timeout_s: float = 600.0,
                 straggler_grace_s: "float | None" = None):
        self.devices_per_process = devices_per_process
        self.platform = platform
        self.timeout_s = timeout_s
        #: Rank watchdog (reliability layer): once the FIRST rank exits,
        #: surviving ranks get this many extra seconds before they are
        #: torn down as hung. An SPMD job's ranks finish near-together;
        #: a rank still running long after its peers is wedged in a
        #: collective its peers already left (e.g. the metrics rollup
        #: when a sibling died uncleanly) and would otherwise block the
        #: job for the full timeout_s. None disables (default: legit
        #: skew — rank 0 pickling a large result — must not be killed
        #: by an over-eager default).
        self.straggler_grace_s = straggler_grace_s

    def run(self, nprocs: int, fn: Callable, kwargs: dict,
            verbosity: str = "all") -> Any:
        import cloudpickle

        env_overrides = {}
        if self.platform == "cpu":
            # always pin the child's device count — devices_per_process=1
            # must MEAN one device even when the parent env carries a
            # --xla_force_host_platform_device_count (the test harness
            # does), else children silently inherit the parent's topology
            env_overrides = virtual_cpu_overrides(
                self.devices_per_process, os.environ.get("XLA_FLAGS", "")
            )
        elif self.platform:
            env_overrides["JAX_PLATFORMS"] = self.platform

        workdir = tempfile.mkdtemp(prefix="sparkdl_tpu_run_")
        payload_path = os.path.join(workdir, "payload.pkl")
        result_path = os.path.join(workdir, "result.pkl")
        with open(payload_path, "wb") as f:
            cloudpickle.dump({"fn": fn, "kwargs": kwargs}, f)

        coordinator = f"localhost:{free_port()}"
        # children must resolve the same modules as the parent (the user fn
        # may be pickled by reference to a module only on the parent's path)
        child_env = os.environ.copy()
        child_env["PYTHONPATH"] = os.pathsep.join(
            [p for p in sys.path if p] + [child_env.get("PYTHONPATH", "")]
        ).rstrip(os.pathsep)
        # Env overrides ride the process env, not the payload: they must be
        # in place before the child interpreter starts (sitecustomize may
        # import jax at startup, long before the worker unpickles anything).
        child_env.update(env_overrides)
        procs: list[subprocess.Popen] = []
        streams: list[threading.Thread] = []
        try:
            for rank in range(nprocs):
                p = subprocess.Popen(
                    [
                        sys.executable, "-m", "sparkdl_tpu.runner._worker",
                        payload_path, str(rank), str(nprocs), coordinator,
                        result_path,
                    ],
                    stdout=subprocess.PIPE,
                    stderr=subprocess.STDOUT,
                    text=True,
                    env=child_env,
                )
                procs.append(p)
                t = threading.Thread(
                    target=_stream_output, args=(p, rank, verbosity), daemon=True
                )
                t.start()
                streams.append(t)

            failed = _wait_all(procs, self.timeout_s,
                               self.straggler_grace_s)
            for t in streams:
                t.join(timeout=5)
            if failed:
                ranks = ", ".join(str(r) for r in failed)
                raise RuntimeError(
                    f"TPURunner local job failed on rank(s) {ranks} "
                    f"(barrier semantics: whole job aborted)"
                )
            return _load_result(result_path)
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
            shutil.rmtree(workdir, ignore_errors=True)


def _stream_output(proc: subprocess.Popen, rank: int, verbosity: str) -> None:
    assert proc.stdout is not None
    for line in proc.stdout:
        if verbosity == "all":
            print(f"[rank {rank}] {line}", end="", flush=True)
        else:
            logger.debug("[rank %d] %s", rank, line.rstrip())


def _wait_all(procs: list[subprocess.Popen], timeout_s: float,
              straggler_grace_s: "float | None" = None) -> list[int]:
    """Wait for every rank; on first failure or timeout kill the rest.

    ``straggler_grace_s`` is the rank watchdog: once the first rank has
    exited (cleanly), ranks still running past the grace window are
    declared hung and torn down — without it a single wedged rank holds
    the job until the global ``timeout_s``.

    Returns the list of failed ranks (empty on success).
    """
    import time

    deadline = time.monotonic() + timeout_s
    pending = dict(enumerate(procs))
    failed: list[int] = []
    first_exit_at: "float | None" = None
    while pending and not failed:
        for rank, p in list(pending.items()):
            rc = p.poll()
            if rc is None:
                continue
            del pending[rank]
            if rc != 0:
                failed.append(rank)
        now = time.monotonic()
        if (pending and first_exit_at is None
                and len(pending) < len(procs)):
            first_exit_at = now
        if (pending and not failed
                and straggler_grace_s is not None
                and first_exit_at is not None
                and now > first_exit_at + straggler_grace_s):
            logger.error(
                "rank watchdog: rank(s) %s still running %.1fs after "
                "the first rank exited; tearing down as hung",
                sorted(pending), straggler_grace_s,
            )
            failed.extend(pending.keys())
            break
        if now > deadline:
            failed.extend(pending.keys())
            break
        time.sleep(0.05)
    for p in pending.values():
        p.kill()
    return sorted(failed)


def _load_result(result_path: str) -> Any:
    if not os.path.exists(result_path):
        raise RuntimeError("rank 0 produced no result file")
    with open(result_path, "rb") as f:
        status, value = pickle.load(f)
    if status == "unpicklable":
        raise RuntimeError(
            f"rank 0's return value could not be pickled: {value}"
        )
    return value


def _host_sort_key(hostname: str) -> tuple:
    """Natural sort key: digit runs compare numerically.

    TPU-VM worker hostnames carry the worker index as a trailing integer
    (``...-w-0``, ``...-w-1``, ... ``...-w-10``); natural order makes
    rank assignment follow the TPU process topology, and plain string sort
    would put ``-w-10`` before ``-w-2``.
    """
    import re

    return tuple(
        int(part) if part.isdigit() else part
        for part in re.split(r"(\d+)", hostname)
    )


def resolve_ranks(addrs: list[str]) -> tuple[list[int], str]:
    """Map barrier-task rendezvous addresses to stable JAX process ids.

    ``addrs[i]`` is partition *i*'s ``host:port``. Returns
    ``(rank_of_partition, coordinator_address)`` where
    ``rank_of_partition[i]`` is the jax process_id partition *i* must use.

    Ranks are assigned by natural-sorted hostname, NOT by Spark partition
    id (SURVEY.md §7 hard part 2): a barrier stage retry may land
    partitions on different executors, but a given TPU host always
    resolves to the same rank as long as the host set is unchanged — so
    rank↔chip binding (and any rank-keyed checkpoint state) survives
    retries. The coordinator is whichever host sorts first.

    Exactly one task per host is enforced here: two barrier tasks on one
    host would each grab the host's TPU runtime and deadlock it. The fix
    on a real cluster is one executor per TPU host (spark.task.cpus =
    executor cores, or spark.executor.cores tuned so one slot per host).
    """
    hosts = [a.rsplit(":", 1)[0] for a in addrs]
    dupes = sorted({h for h in hosts if hosts.count(h) > 1})
    if dupes:
        raise RuntimeError(
            f"barrier placement error: multiple tasks on host(s) "
            f"{', '.join(dupes)} — TPURunner needs exactly one barrier "
            f"task per TPU host (set spark.task.cpus == executor cores so "
            f"each executor runs one task, one executor per host)"
        )
    order = sorted(range(len(addrs)), key=lambda i: _host_sort_key(hosts[i]))
    rank_of_partition = [0] * len(addrs)
    for rank, part in enumerate(order):
        rank_of_partition[part] = rank
    return rank_of_partition, addrs[order[0]]


def run_barrier_task(
    ctx,
    payload: bytes,
    nprocs: int,
    preflight_opts: dict,
    log_addr: "str | None" = None,
    hostname: "str | None" = None,
    distributed_init: "Callable | None" = None,
) -> bytes:
    """Body of one Spark barrier task, extracted so a faked
    BarrierTaskContext (``partitionId()`` + ``allGather(str)``) can drive
    it in-suite without pyspark (SURVEY.md §4: test semantics locally).

    ``distributed_init(coordinator, nprocs, rank)`` defaults to
    ``jax.distributed.initialize``; tests inject a recorder. Returns rank
    0's pickled result (b"" on other ranks).
    """
    import cloudpickle

    hostname = hostname or socket.gethostname()
    port = free_port()
    addrs = list(ctx.allGather(f"{hostname}:{port}"))
    if len(addrs) != nprocs:
        raise RuntimeError(
            f"rendezvous returned {len(addrs)} addresses for {nprocs} tasks"
        )
    rank_of_partition, coordinator = resolve_ranks(addrs)
    rank = rank_of_partition[ctx.partitionId()]

    with _ShipOutput(log_addr, rank):
        if distributed_init is None:
            import jax

            try:
                jax.distributed.initialize(
                    coordinator_address=coordinator,
                    num_processes=nprocs,
                    process_id=rank,
                )
            except Exception as e:
                # Most likely cause: the coordinator port advertised at
                # rendezvous got taken between free_port() and the bind
                # here. Barrier stages are all-or-nothing — failing the
                # task makes Spark retry the whole stage, which re-runs
                # the rendezvous with a fresh port.
                raise RuntimeError(
                    f"jax.distributed.initialize failed on rank {rank} "
                    f"(coordinator {coordinator}): {e}. If this is a port "
                    f"collision the stage retry re-rendezvouses cleanly."
                ) from e
        else:
            distributed_init(coordinator, nprocs, rank)
        # Slice health probe before the user fn compiles anything: a bad
        # chip fails this barrier task now, and Spark's stage retry plus
        # checkpoint resume (sparkdl_tpu.checkpoint) handle the rest.
        from sparkdl_tpu.observability.health import preflight

        preflight(rank=rank, **preflight_opts)
        p = cloudpickle.loads(payload)
        out = p["fn"](**p["kwargs"])
    return pickle.dumps(out) if rank == 0 else b""


def _get_barrier_context():
    """Executor-side hook returning the live barrier context; module-level
    so suites without pyspark can monkeypatch a fake in under the REAL
    ``SparkBarrierBackend.run`` body."""
    from pyspark import BarrierTaskContext

    return BarrierTaskContext.get()


class _LogRelay:
    """Driver-side TCP line sink for executor stdout (HorovodRunner's
    ``driver_log_verbosity`` equivalent, SURVEY.md 2.13).

    Executors already need driver connectivity in Spark (block manager,
    barrier coordination), so a plain listening socket on the driver is
    reachable wherever Spark itself works. Each task connects once and
    streams ``[rank N] ...`` lines; the relay prints them into the driver
    log as they arrive.
    """

    def __init__(self, sink: "Callable[[str], None] | None" = None,
                 keep_lines: int = 10_000):
        import collections

        self._sink = sink or (lambda line: print(line, flush=True))
        #: bounded tail of forwarded lines (test/inspection hook; the full
        #: stream goes to the sink) — unbounded would leak driver memory
        #: over a long job's worth of executor output.
        self.lines: "collections.deque[str]" = collections.deque(
            maxlen=keep_lines)
        self._srv = socket.socket()
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind(("", 0))
        self._srv.listen(64)
        self._srv.settimeout(0.2)
        self.address = f"{socket.gethostname()}:{self._srv.getsockname()[1]}"
        self._closing = threading.Event()
        #: live pump threads only — each pump removes itself on disconnect,
        #: so a long job's worth of short-lived connections does not
        #: accumulate one dead Thread object per connection
        self._pumps: "set[threading.Thread]" = set()
        self._pumps_lock = threading.Lock()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True)
        self._accept_thread.start()

    @property
    def live_pumps(self) -> int:
        """Number of currently-connected executor streams."""
        with self._pumps_lock:
            return len(self._pumps)

    def _accept_loop(self) -> None:
        while not self._closing.is_set():
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            t = threading.Thread(
                target=self._pump, args=(conn,), daemon=True
            )
            with self._pumps_lock:
                self._pumps.add(t)
            t.start()

    def _pump(self, conn: socket.socket) -> None:
        try:
            with conn, conn.makefile("r", errors="replace") as f:
                for line in f:
                    line = line.rstrip("\n")
                    self.lines.append(line)
                    self._sink(line)
        finally:
            with self._pumps_lock:
                self._pumps.discard(threading.current_thread())

    def close(self) -> None:
        self._closing.set()
        try:
            self._srv.close()
        except OSError:
            pass
        self._accept_thread.join(timeout=2)
        with self._pumps_lock:
            pumps = list(self._pumps)
        for t in pumps:
            t.join(timeout=2)


class _ShipOutput:
    """Executor-side context manager: tee this process's stdout/stderr to
    the driver's :class:`_LogRelay` while the user fn runs.

    File-descriptor level (dup2), so native prints (XLA, C++ bridge) ship
    too, not just Python ``print``. Lines still reach the executor's own
    log via the tee. No-op when ``addr`` is None (verbosity 'none') or the
    relay is unreachable — log forwarding must never fail the job.
    """

    def __init__(self, addr: "str | None", rank: int):
        self.addr = addr
        self.rank = rank
        self._sock = None
        self._saved: list[tuple[int, int]] = []
        self._pump_thread = None

    def __enter__(self):
        if self.addr is None:
            return self
        try:
            host, port = self.addr.rsplit(":", 1)
            self._sock = socket.create_connection((host, int(port)), timeout=5)
        except OSError:
            self._sock = None
            return self
        r, w = os.pipe()
        self._saved = [(1, os.dup(1)), (2, os.dup(2))]
        os.dup2(w, 1)
        os.dup2(w, 2)
        os.close(w)
        self._pump_thread = threading.Thread(
            target=self._pump, args=(r,), daemon=True
        )
        self._pump_thread.start()
        return self

    def _pump(self, rfd: int) -> None:
        orig_out = self._saved[0][1]
        buf = b""
        with os.fdopen(rfd, "rb", closefd=True) as r:
            while True:
                chunk = r.read1(65536)
                if not chunk:
                    break
                os.write(orig_out, chunk)  # tee to the executor's own log
                buf += chunk
                *lines, buf = buf.split(b"\n")
                for line in lines:
                    self._send(line)
        if buf:
            self._send(buf)

    def _send(self, line: bytes) -> None:
        if self._sock is None:
            return
        try:
            self._sock.sendall(b"[rank %d] %s\n" % (self.rank, line))
        except OSError:
            try:
                self._sock.close()
            finally:
                self._sock = None

    def __exit__(self, *exc):
        if not self._saved:
            if self._sock is not None:
                self._sock.close()
            return False
        sys.stdout.flush()
        sys.stderr.flush()
        # Restore first: dropping the last write-end refs of the pipe EOFs
        # the pump; only close the saved duplicates after the pump (which
        # tees through one of them) has drained.
        for fd, saved in self._saved:
            os.dup2(saved, fd)
        if self._pump_thread is not None:
            self._pump_thread.join(timeout=5)
        for _, saved in self._saved:
            os.close(saved)
        self._saved = []
        if self._sock is not None:
            self._sock.close()
            self._sock = None
        return False


class SparkBarrierBackend:
    """np>0 mode: one barrier task per TPU host via a live SparkSession.

    The task body (:func:`run_barrier_task`) rendezvouses through
    ``BarrierTaskContext.allGather`` (each task publishes ``host:port``),
    resolves stable hostname-ordered ranks, calls
    ``jax.distributed.initialize`` with the coordinator, runs the user fn
    with stdout teed to the driver, and returns rank 0's result — the
    reference's mpirun bootstrap replaced by coordinator address exchange
    (SURVEY.md §5 "Distributed communication backend").
    """

    def __init__(self, spark_session=None):
        if spark_session is None:
            from pyspark.sql import SparkSession

            spark_session = SparkSession.getActiveSession()
        if spark_session is None:
            raise RuntimeError(
                "no active SparkSession; np>0 needs a cluster (or use np<0 "
                "local mode)"
            )
        self.spark = spark_session

    def run(self, nprocs: int, fn: Callable, kwargs: dict,
            verbosity: str = "all") -> Any:
        import cloudpickle

        payload = cloudpickle.dumps({"fn": fn, "kwargs": kwargs})
        sc = self.spark.sparkContext
        # Preflight knobs resolve on the DRIVER (executor environments don't
        # inherit the driver's env) and ride the task closure.
        from sparkdl_tpu.observability.health import preflight_env_opts

        preflight_opts = preflight_env_opts()
        relay = _LogRelay() if verbosity == "all" else None
        log_addr = relay.address if relay is not None else None

        def barrier_task(it):
            ctx = _get_barrier_context()
            yield run_barrier_task(
                ctx, payload, nprocs, preflight_opts, log_addr=log_addr
            )

        try:
            results = (
                sc.parallelize(range(nprocs), nprocs)
                .barrier()
                .mapPartitions(barrier_task)
                .collect()
            )
        finally:
            if relay is not None:
                relay.close()
        ranked = [r for r in results if r]
        if not ranked:
            raise RuntimeError("no rank returned a result")
        return pickle.loads(ranked[0])
