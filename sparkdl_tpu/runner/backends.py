"""Cluster backends for TPURunner: local processes and Spark barrier jobs.

Reference parity (SURVEY.md 2.13/3.4): HorovodRunner's two regimes —
``np < 0`` local debug processes, ``np > 0`` Spark barrier tasks with an
MPI rendezvous — map here to :class:`LocalProcessBackend` (subprocesses on
this host) and :class:`SparkBarrierBackend` (one barrier task per TPU host,
rendezvous via ``BarrierTaskContext.allGather``). Both end in
``jax.distributed.initialize``: in-step gradient comm is XLA collectives
over ICI/DCN compiled into the program, so there is no user-space ring to
bootstrap — only the coordinator address exchange.
"""

from __future__ import annotations

import logging
import os
import pickle
import shutil
import socket
import subprocess
import sys
import tempfile
import threading
from typing import Any, Callable

logger = logging.getLogger(__name__)


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def virtual_cpu_overrides(n_devices: int, existing_flags: str = "") -> dict:
    """Env overrides forcing an ``n_devices``-way virtual CPU platform.

    The single source of truth for the "fake mesh" env contract used by the
    test conftest, LocalProcessBackend children, and the graft-entry
    dry-run re-exec: ``JAX_PLATFORMS=cpu`` plus
    ``--xla_force_host_platform_device_count`` (any existing count flag in
    ``existing_flags`` is replaced, not duplicated). Overrides must be in
    place before the target process initializes a jax backend.
    """
    flags = [
        f
        for f in existing_flags.split()
        if not f.startswith("--xla_force_host_platform_device_count")
    ]
    flags.append(f"--xla_force_host_platform_device_count={n_devices}")
    return {"JAX_PLATFORMS": "cpu", "XLA_FLAGS": " ".join(flags)}


class LocalProcessBackend:
    """Run n ranks as subprocesses of this host (HorovodRunner np<0 mode).

    Each rank is a fresh interpreter (env must precede jax import). By
    default ranks run on CPU with ``devices_per_process`` fake devices each,
    so multi-process collective code is debuggable on one machine with (or
    without) a single TPU chip.
    """

    def __init__(self, devices_per_process: int = 1, platform: "str | None" = "cpu",
                 timeout_s: float = 600.0):
        self.devices_per_process = devices_per_process
        self.platform = platform
        self.timeout_s = timeout_s

    def run(self, nprocs: int, fn: Callable, kwargs: dict,
            verbosity: str = "all") -> Any:
        import cloudpickle

        env_overrides = {}
        if self.platform == "cpu" and self.devices_per_process > 1:
            env_overrides = virtual_cpu_overrides(
                self.devices_per_process, os.environ.get("XLA_FLAGS", "")
            )
        elif self.platform:
            env_overrides["JAX_PLATFORMS"] = self.platform

        workdir = tempfile.mkdtemp(prefix="sparkdl_tpu_run_")
        payload_path = os.path.join(workdir, "payload.pkl")
        result_path = os.path.join(workdir, "result.pkl")
        with open(payload_path, "wb") as f:
            cloudpickle.dump({"fn": fn, "kwargs": kwargs}, f)

        coordinator = f"localhost:{free_port()}"
        # children must resolve the same modules as the parent (the user fn
        # may be pickled by reference to a module only on the parent's path)
        child_env = os.environ.copy()
        child_env["PYTHONPATH"] = os.pathsep.join(
            [p for p in sys.path if p] + [child_env.get("PYTHONPATH", "")]
        ).rstrip(os.pathsep)
        # Env overrides ride the process env, not the payload: they must be
        # in place before the child interpreter starts (sitecustomize may
        # import jax at startup, long before the worker unpickles anything).
        child_env.update(env_overrides)
        procs: list[subprocess.Popen] = []
        streams: list[threading.Thread] = []
        try:
            for rank in range(nprocs):
                p = subprocess.Popen(
                    [
                        sys.executable, "-m", "sparkdl_tpu.runner._worker",
                        payload_path, str(rank), str(nprocs), coordinator,
                        result_path,
                    ],
                    stdout=subprocess.PIPE,
                    stderr=subprocess.STDOUT,
                    text=True,
                    env=child_env,
                )
                procs.append(p)
                t = threading.Thread(
                    target=_stream_output, args=(p, rank, verbosity), daemon=True
                )
                t.start()
                streams.append(t)

            failed = _wait_all(procs, self.timeout_s)
            for t in streams:
                t.join(timeout=5)
            if failed:
                ranks = ", ".join(str(r) for r in failed)
                raise RuntimeError(
                    f"TPURunner local job failed on rank(s) {ranks} "
                    f"(barrier semantics: whole job aborted)"
                )
            return _load_result(result_path)
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
            shutil.rmtree(workdir, ignore_errors=True)


def _stream_output(proc: subprocess.Popen, rank: int, verbosity: str) -> None:
    assert proc.stdout is not None
    for line in proc.stdout:
        if verbosity == "all":
            print(f"[rank {rank}] {line}", end="", flush=True)
        else:
            logger.debug("[rank %d] %s", rank, line.rstrip())


def _wait_all(procs: list[subprocess.Popen], timeout_s: float) -> list[int]:
    """Wait for every rank; on first failure or timeout kill the rest.

    Returns the list of failed ranks (empty on success).
    """
    import time

    deadline = time.monotonic() + timeout_s
    pending = dict(enumerate(procs))
    failed: list[int] = []
    while pending and not failed:
        for rank, p in list(pending.items()):
            rc = p.poll()
            if rc is None:
                continue
            del pending[rank]
            if rc != 0:
                failed.append(rank)
        if time.monotonic() > deadline:
            failed.extend(pending.keys())
            break
        time.sleep(0.05)
    for p in pending.values():
        p.kill()
    return sorted(failed)


def _load_result(result_path: str) -> Any:
    if not os.path.exists(result_path):
        raise RuntimeError("rank 0 produced no result file")
    with open(result_path, "rb") as f:
        status, value = pickle.load(f)
    if status == "unpicklable":
        raise RuntimeError(
            f"rank 0's return value could not be pickled: {value}"
        )
    return value


class SparkBarrierBackend:
    """np>0 mode: one barrier task per TPU host via a live SparkSession.

    The task body rendezvouses through ``BarrierTaskContext.allGather``
    (rank 0 publishes ``host:port``), calls ``jax.distributed.initialize``
    with that coordinator, runs the user fn, and returns rank 0's result to
    the driver — the reference's mpirun bootstrap replaced by coordinator
    address exchange (SURVEY.md §5 "Distributed communication backend").
    """

    def __init__(self, spark_session=None):
        if spark_session is None:
            from pyspark.sql import SparkSession

            spark_session = SparkSession.getActiveSession()
        if spark_session is None:
            raise RuntimeError(
                "no active SparkSession; np>0 needs a cluster (or use np<0 "
                "local mode)"
            )
        self.spark = spark_session

    def run(self, nprocs: int, fn: Callable, kwargs: dict,
            verbosity: str = "all") -> Any:
        import cloudpickle

        payload = cloudpickle.dumps({"fn": fn, "kwargs": kwargs})
        sc = self.spark.sparkContext
        # Preflight knobs resolve on the DRIVER (executor environments don't
        # inherit the driver's env) and ride the task closure.
        from sparkdl_tpu.observability.health import preflight_env_opts

        preflight_opts = preflight_env_opts()

        def barrier_task(it):
            from pyspark import BarrierTaskContext

            ctx = BarrierTaskContext.get()
            rank = ctx.partitionId()
            port = free_port()
            addrs = ctx.allGather(f"{socket.gethostname()}:{port}")
            coordinator = addrs[0]

            import jax

            jax.distributed.initialize(
                coordinator_address=coordinator,
                num_processes=nprocs,
                process_id=rank,
            )
            # Slice health probe before the user fn compiles anything: a bad
            # chip fails this barrier task now, and Spark's stage retry plus
            # checkpoint resume (sparkdl_tpu.checkpoint) handle the rest.
            from sparkdl_tpu.observability.health import preflight

            preflight(rank=rank, **preflight_opts)
            p = cloudpickle.loads(payload)
            out = p["fn"](**p["kwargs"])
            yield pickle.dumps(out) if rank == 0 else b""

        results = (
            sc.parallelize(range(nprocs), nprocs)
            .barrier()
            .mapPartitions(barrier_task)
            .collect()
        )
        return pickle.loads(results[0])
