"""Worker-process entry point for TPURunner's local-process backend.

Launched as ``python -m sparkdl_tpu.runner._worker <payload> <rank> <np>
<coordinator> <result_path>``. The payload (cloudpickle) carries the user fn
and kwargs; env overrides (JAX_PLATFORMS, XLA_FLAGS, ...) are set by the
parent in this process's environment before exec, so they are in place
before any import (sitecustomize may import jax at interpreter start).
"""

from __future__ import annotations

import os
import pickle
import sys
import traceback


def main(argv: list[str]) -> int:
    payload_path, rank_s, np_s, coordinator, result_path = argv
    rank, nprocs = int(rank_s), int(np_s)

    import cloudpickle

    with open(payload_path, "rb") as f:
        payload = cloudpickle.load(f)

    # Env overrides (JAX_PLATFORMS, XLA_FLAGS, ...) arrive via the process
    # environment, set by the parent before exec — nothing to apply here.
    import jax

    # sitecustomize may have imported jax already with another platform
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=nprocs,
        process_id=rank,
    )

    # Pre-flight slice health probe + optional profiler server (SURVEY.md §5
    # failure detection): fail fast if a chip or the collective path is bad,
    # before the user's train_fn compiles anything. Local mode shares the
    # parent's host, so the env knobs (SPARKDL_TPU_SKIP_HEALTH_CHECK /
    # SPARKDL_TPU_PROFILER_PORT) are read right here.
    from sparkdl_tpu.observability.health import preflight, preflight_env_opts

    try:
        preflight(rank=rank, **preflight_env_opts())
    except RuntimeError:
        return 2

    fn = payload["fn"]
    kwargs = payload["kwargs"]
    try:
        # Deterministic rank-crash site (reliability/faults.py): the plan
        # rides the inherited environment (SPARKDL_TPU_FAULT_PLAN), so a
        # parent can arm "worker.rank" (any rank — each child counts its
        # own hits) or "worker.rank.<r>" (that rank only) and the child
        # kills itself — the preemption drill for the backend's
        # peer-teardown watchdog.
        from sparkdl_tpu.reliability.faults import fault_point

        fault_point("worker.rank")
        fault_point(f"worker.rank.{rank}")
        result = fn(**kwargs)
    except Exception:
        traceback.print_exc()
        return 1

    if rank == 0:
        with open(result_path, "wb") as f:
            try:
                pickle.dump(("ok", result), f)
            except Exception as e:  # unpicklable user return value
                f.seek(0)
                pickle.dump(("unpicklable", repr(e)), f)
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
