from sparkdl_tpu.runner.tpu_runner import HorovodRunner, TPURunner

__all__ = ["TPURunner", "HorovodRunner"]
