"""Subprocess entry for process-isolated HPO trials (hpo.fmin
trial_runner='processes'): unpickle (objective, params), evaluate, write
the result dict back. A fresh interpreter per trial gives each one its
own jax runtime/devices — the single-host analogue of SparkTrials'
executor-side evaluation."""

from __future__ import annotations

import sys


def main(payload_path: str, result_path: str) -> int:
    import cloudpickle

    with open(payload_path, "rb") as f:
        payload = cloudpickle.load(f)
    objective, params = payload["objective"], payload["params"]
    try:
        out = objective(params)
        loss = out["loss"] if isinstance(out, dict) else float(out)
        extra = out if isinstance(out, dict) else {}
        result = {"loss": float(loss), "status": "ok",
                  **{k: v for k, v in extra.items()
                     if k not in ("loss", "status")}}
    except Exception as e:  # the parent records the failure, sweep survives
        result = {"loss": None, "status": "fail", "error": repr(e)}
    with open(result_path, "wb") as f:
        cloudpickle.dump(result, f)
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1], sys.argv[2]))
