"""Max-pool with a bandwidth-friendly backward (no select_and_scatter).

XLA lowers the gradient of ``reduce_window(max)`` to ``select_and_scatter``
— measured at 2.4 ms/step in ResNet50 training (PERF.md "Training MFU"),
far off the op's ~0.5 ms bandwidth bound, because the scatter serializes
per window. This module's ``max_pool`` keeps the identical forward (XLA
``reduce_window``) but swaps the backward for a gather formulation: for
each window tap ``t``, the gradient flows to the input position holding
the window's max — first occurrence in row-major window order, matching
select_and_scatter's GE-select tie-breaking exactly — expressed as W·W
shifted compares + dilated pads that XLA fuses into plain elementwise
loops.

Forward semantics match ``flax.linen.max_pool`` (VALID padding).

MEASURED NEGATIVE RESULT (round 3, kept for the record): in the full
ResNet50 train program this backward is ~2x slower than
select_and_scatter (26.6%→22.9% MFU when routed globally) — the
first-tap mask materializes an s32 map at output shape and the 9-tap
dilated accumulation does not fuse into one pass. The zoo models
therefore stay on ``nn.max_pool``; this op remains available (and
oracle-exact, incl. tie-breaking) for programs where the forward max is
already resident and the s32 map amortizes.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def max_pool(x, window: int = 3, strides: int = 2):
    """NHWC max pool, VALID padding; backward avoids select_and_scatter."""
    return _forward(x, window, strides)


def _forward(x, window, strides):
    init = (-jnp.inf if jnp.issubdtype(x.dtype, jnp.floating)
            else jnp.iinfo(x.dtype).min)
    return lax.reduce_window(
        x, init, lax.max,
        (1, window, window, 1), (1, strides, strides, 1), "VALID",
    )


def _tap(x, di, dj, strides, oh, ow):
    """View of x aligned with windows at tap (di, dj): [B, OH, OW, C]."""
    return lax.slice(
        x,
        (0, di, dj, 0),
        (x.shape[0], di + (oh - 1) * strides + 1,
         dj + (ow - 1) * strides + 1, x.shape[3]),
        (1, strides, strides, 1),
    )


def _fwd_rule(x, window, strides):
    y = _forward(x, window, strides)
    return y, (x, y)


def _bwd_rule(window, strides, res, dy):
    x, y = res
    b, ih, iw, c = x.shape
    oh, ow = y.shape[1], y.shape[2]
    big = window * window
    # first tap (row-major order) achieving the max, per window — the
    # position select_and_scatter's GE-select would pick
    first = jnp.full(y.shape, big, jnp.int32)
    order = 0
    for di in range(window):
        for dj in range(window):
            eq = _tap(x, di, dj, strides, oh, ow) == y
            first = jnp.minimum(first, jnp.where(eq, order, big))
            order += 1

    # accumulate in dy's dtype: at most ceil(w/s)^2 contributions overlap
    # per input position, and the f32 alternative doubles the HBM traffic
    # of the hottest backward array in the net (measured: the f32
    # [256,114,114,64] accumulation fusion cost 5.9 ms/step on chip)
    zero = jnp.zeros((), dy.dtype)
    dx = jnp.zeros((b, ih, iw, c), dy.dtype)
    order = 0
    for di in range(window):
        for dj in range(window):
            contrib = jnp.where(first == order, dy, zero)
            # scatter back to input positions: dilate by the stride and
            # offset by the tap — overlapping windows accumulate via +
            hi_h = ih - (di + (oh - 1) * strides + 1)
            hi_w = iw - (dj + (ow - 1) * strides + 1)
            dx = dx + lax.pad(
                contrib, zero,
                ((0, 0, 0), (di, hi_h, strides - 1),
                 (dj, hi_w, strides - 1), (0, 0, 0)),
            )
            order += 1
    return (dx.astype(x.dtype),)


max_pool.defvjp(_fwd_rule, _bwd_rule)
