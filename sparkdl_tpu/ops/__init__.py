from sparkdl_tpu.ops.flash_attention import flash_attention
from sparkdl_tpu.ops.preprocess import (
    PREPROCESSORS,
    preprocess_caffe,
    preprocess_identity,
    preprocess_tf,
    preprocess_torch,
    resize_images,
)

__all__ = [
    "PREPROCESSORS",
    "flash_attention",
    "preprocess_caffe",
    "preprocess_identity",
    "preprocess_tf",
    "preprocess_torch",
    "resize_images",
]
