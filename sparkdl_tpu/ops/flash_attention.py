"""Pallas TPU flash attention: fused blockwise softmax attention.

The reference runs attention inside opaque TF graphs on GPU (SURVEY.md
§2.18 — libtensorflow kernel dispatch); here the hot op is a hand-written
Pallas kernel tiled for the MXU: Q/K/V blocks stream HBM→VMEM, scores and
probabilities live only in VMEM scratch (never materialised at [L, L] in
HBM), and the online-softmax running (max, denominator) accumulators ride
along in VMEM across the K-block grid dimension. Forward saves only the
per-row logsumexp; the backward pass recomputes probabilities blockwise in
two further kernels (dq; dk/dv), the standard flash-attention trade of
FLOPs for HBM bandwidth — the right trade on TPU where HBM is the
bottleneck and the MXU is rarely saturated by attention.

TPU layout notes: row-statistics (logsumexp, the dO·O correction term)
travel in an all-lanes-equal [*, L, 128] layout so kernel reads/writes
never need a cross-lane transpose; the key-padding mask travels as
[BH, 1, L] (a legal block shape because its sublane dim equals the array
dim). The dk/dv kernel contracts over the sublane dim via dot_general
instead of materialising transposed score blocks.

Public layout: [B, L, H, D] (matching ``parallel.ring_attention``), folded
to [B*H, L, D] for the kernels. Supports causal masking and a [B, Lk] bool
key-padding mask; attention-probs dropout is unsupported (the usual
flash-attention trade-off, same caveat as the ring path).

On CPU (tests; the reference-parity virtual-mesh harness) the kernels run
in Pallas interpreter mode automatically.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

_NEG_INF = -1e30  # large-negative, not -inf: keeps exp()/where() NaN-free
_LANES = 128  # TPU lane width: last-dim tile size


@dataclasses.dataclass(frozen=True)
class _Config:
    """Static kernel configuration (hashable: custom_vjp nondiff arg)."""

    scale: float
    causal: bool
    block_q: int
    block_k: int
    interpret: bool
    #: global position of query row 0 (cached prefill: queries sit at
    #: [q_offset, q_offset+Lq) against keys at [0, Lk))
    q_offset: int = 0


def _ceil_to(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _vmem(shape, dtype):
    from jax.experimental.pallas import tpu as pltpu

    return pltpu.VMEM(shape, dtype)


def _causal_mask(s, qi, ki, bq, bk, q_offset=0):
    q_pos = (q_offset + qi * bq
             + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0))
    k_pos = ki * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return jnp.where(q_pos >= k_pos, s, _NEG_INF)


# ---------------------------------------------------------------------------
# forward kernel
# ---------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, mask_ref, o_ref, lse_ref,
                acc_scr, m_scr, l_scr, *, cfg: _Config):
    """Grid (bh, q_blocks, k_blocks); k innermost so VMEM scratch carries
    the online-softmax state across K blocks for one Q block."""
    qi, ki = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)
    bq, bk = cfg.block_q, cfg.block_k

    @pl.when(ki == 0)
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr)
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    # Causal: skip K blocks strictly above the diagonal band.
    run = True
    if cfg.causal:
        run = ki * bk <= cfg.q_offset + qi * bq + bq - 1

    @pl.when(run)
    def _attend():
        # Operands stay in their storage dtype (bf16): the MXU computes
        # bf16 x bf16 with f32 accumulate natively; upcasting first would
        # force 6-pass f32 matmuls (measured ~6x slower on v5e).
        q = q_ref[0]  # [bq, d]
        k = k_ref[0]  # [bk, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * cfg.scale  # [bq, bk] f32
        s = jnp.where(mask_ref[0] != 0, s, _NEG_INF)  # [1, bk] broadcast
        if cfg.causal:
            s = _causal_mask(s, qi, ki, bq, bk, cfg.q_offset)

        m_prev = m_scr[:]  # [bq, LANES] (all lanes equal)
        l_prev = l_scr[:]
        m_cur = jnp.max(s, axis=-1, keepdims=True)  # [bq, 1]
        m_next = jnp.maximum(m_prev, m_cur)  # broadcast → [bq, LANES]
        correction = jnp.exp(m_prev[:, :1] - m_next[:, :1])  # [bq, 1]
        p = jnp.exp(s - m_next[:, :1])  # [bq, bk]
        l_scr[:] = l_prev * correction + jnp.sum(p, axis=-1, keepdims=True)
        m_scr[:] = m_next
        v = v_ref[0]  # [bk, d] storage dtype
        # Probabilities drop to the V dtype for the PV matmul (the
        # standard flash trade); accumulation stays f32 in scratch.
        acc_scr[:] = acc_scr[:] * correction + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(ki == nk - 1)
    def _finalize():
        l = jnp.maximum(l_scr[:], 1e-30)  # [bq, LANES]
        o_ref[0] = (acc_scr[:] / l[:, :1]).astype(o_ref.dtype)
        lse_ref[0] = m_scr[:] + jnp.log(l)  # all lanes equal


def _fwd(cfg: _Config, q, k, v, mask):
    """q,k,v: [BH, L, D] (padded); mask: [BH, 1, Lk] int32.

    Returns (o [BH, Lq, D], lse [BH, Lq, LANES] all-lanes-equal).
    """
    bh, lq, d = q.shape
    lk = k.shape[1]
    bq, bk = cfg.block_q, cfg.block_k
    return pl.pallas_call(
        functools.partial(_fwd_kernel, cfg=cfg),
        grid=(bh, lq // bq, lk // bk),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, 1, bk), lambda b, i, j: (b, 0, j)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, _LANES), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, lq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, lq, _LANES), jnp.float32),
        ],
        scratch_shapes=[
            _vmem((bq, d), jnp.float32),
            _vmem((bq, _LANES), jnp.float32),
            _vmem((bq, _LANES), jnp.float32),
        ],
        interpret=cfg.interpret,
    )(q, k, v, mask)


# ---------------------------------------------------------------------------
# backward kernels
# ---------------------------------------------------------------------------


def _recompute_p(q_ref, k_ref, mask_ref, lse_ref, qi, ki, cfg):
    """Rebuild the probability block p = exp(s - lse): [bq, bk] f32."""
    s = jax.lax.dot_general(
        q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * cfg.scale
    s = jnp.where(mask_ref[0] != 0, s, _NEG_INF)
    if cfg.causal:
        s = _causal_mask(s, qi, ki, cfg.block_q, cfg.block_k,
                         cfg.q_offset)
    return jnp.exp(s - lse_ref[0][:, :1])


def _bwd_dq_kernel(q_ref, k_ref, v_ref, mask_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, dq_scr, *, cfg: _Config):
    """Grid (bh, q_blocks, k_blocks): accumulate dq for one Q block."""
    qi, ki = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    run = True
    if cfg.causal:
        run = (ki * cfg.block_k
               <= cfg.q_offset + qi * cfg.block_q + cfg.block_q - 1)

    @pl.when(run)
    def _accum():
        p = _recompute_p(q_ref, k_ref, mask_ref, lse_ref, qi, ki, cfg)
        dp = jax.lax.dot_general(
            do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [bq, bk] f32
        ds = p * (dp - delta_ref[0][:, :1]) * cfg.scale
        k = k_ref[0]
        dq_scr[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, mask_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr, *, cfg: _Config):
    """Grid (bh, k_blocks, q_blocks): accumulate dk/dv for one K block.

    All contractions with p/ds run over the sublane (query) dim via
    dot_general, so no transposed score block is ever materialised.
    """
    ki, qi = pl.program_id(1), pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    run = True
    if cfg.causal:
        run = (cfg.q_offset + qi * cfg.block_q + cfg.block_q - 1
               >= ki * cfg.block_k)

    @pl.when(run)
    def _accum():
        p = _recompute_p(q_ref, k_ref, mask_ref, lse_ref, qi, ki, cfg)
        do = do_ref[0]  # [bq, d] storage dtype
        v = v_ref[0]  # [bk, d]
        # dv += p^T @ dO — contract the query dim (sublanes of p).
        dv_scr[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [bq, bk] f32
        ds = (p * (dp - delta_ref[0][:, :1]) * cfg.scale)
        q = q_ref[0]
        # dk += ds^T @ Q — again contracting the query dim.
        dk_scr[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _bwd(cfg: _Config, q, k, v, mask, do, lse, delta):
    """lse/delta: [BH, Lq, LANES] all-lanes-equal."""
    bh, lq, d = q.shape
    lk = k.shape[1]
    bq, bk = cfg.block_q, cfg.block_k
    q_spec = pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0))
    row_spec = pl.BlockSpec((1, bq, _LANES), lambda b, i, j: (b, i, 0))
    k_spec = pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0))
    mask_spec = pl.BlockSpec((1, 1, bk), lambda b, i, j: (b, 0, j))
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, cfg=cfg),
        grid=(bh, lq // bq, lk // bk),
        in_specs=[q_spec, k_spec, k_spec, mask_spec, q_spec, row_spec,
                  row_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((bh, lq, d), q.dtype),
        scratch_shapes=[_vmem((bq, d), jnp.float32)],
        interpret=cfg.interpret,
    )(q, k, v, mask, do, lse, delta)

    # dk/dv: K-block-major grid; Q-indexed operands stream over axis 2.
    kq_spec = pl.BlockSpec((1, bq, d), lambda b, j, i: (b, i, 0))
    krow_spec = pl.BlockSpec((1, bq, _LANES), lambda b, j, i: (b, i, 0))
    kk_spec = pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0))
    kmask_spec = pl.BlockSpec((1, 1, bk), lambda b, j, i: (b, 0, j))
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, cfg=cfg),
        grid=(bh, lk // bk, lq // bq),
        in_specs=[kq_spec, kk_spec, kk_spec, kmask_spec, kq_spec, krow_spec,
                  krow_spec],
        out_specs=[kk_spec, kk_spec],
        out_shape=[
            jax.ShapeDtypeStruct((bh, lk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, lk, d), v.dtype),
        ],
        scratch_shapes=[
            _vmem((bk, d), jnp.float32),
            _vmem((bk, d), jnp.float32),
        ],
        interpret=cfg.interpret,
    )(q, k, v, mask, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom_vjp wrapper over padded [BH, L, D] arrays
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _flash(cfg: _Config, q, k, v, mask):
    o, _ = _fwd(cfg, q, k, v, mask)
    return o


def _flash_fwd(cfg: _Config, q, k, v, mask):
    o, lse = _fwd(cfg, q, k, v, mask)
    # Residual keeps one lane; bwd re-broadcasts (XLA fuses the broadcast
    # into the pallas input copy).
    return o, (q, k, v, mask, o, lse[:, :, 0])


def _flash_bwd(cfg: _Config, res, do):
    q, k, v, mask, o, lse = res
    # delta_i = rowsum(dO_i * O_i): the softmax-jacobian correction term.
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    lse_b = jnp.broadcast_to(lse[..., None], (*lse.shape, _LANES))
    delta_b = jnp.broadcast_to(delta[..., None], (*delta.shape, _LANES))
    dq, dk, dv = _bwd(cfg, q, k, v, mask, do, lse_b, delta_b)
    return dq, dk, dv, np.zeros(mask.shape, jax.dtypes.float0)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    kv_mask: jax.Array | None = None,
    *,
    causal: bool = False,
    scale: float | None = None,
    block_q: int = 512,
    block_k: int = 512,
    q_offset: int = 0,
    interpret: bool | None = None,
) -> jax.Array:
    """Fused flash attention over [B, L, H, D] tensors.

    kv_mask: optional [B, Lk] bool — False key positions (padding) are
    excluded. interpret=None auto-selects Pallas interpreter mode off-TPU.
    Differentiable in q/k/v (blockwise-recomputed backward kernels).
    q_offset (static): global position of query row 0 for the causal
    mask — cached prefill places L queries at [q_offset, q_offset+L)
    against Lk >= L keys at [0, Lk).

    Block sizes default to 512: on real hardware a (bq, bk) program is
    ~bq*bk*d*4 FLOPs against ~microsecond-scale per-program overhead, so
    128-sized blocks leave the MXU idle (measured 7x slower at L=4096 on
    v5e than 512 blocks); short sequences still shrink blocks to the
    padded length.
    """
    b, lq, h, d = q.shape
    lk = k.shape[1]
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    # Pad: L to block multiples (block shrinks to the padded length for
    # short sequences), D to the 128-lane tile. Padded keys are masked;
    # padded Q rows attend real keys (finite lse, so backward stays
    # NaN-free) and are sliced away. Mosaic requires the K block (the lane
    # dim of the score tile) be 128-aligned unless it spans the whole
    # array, so compiled mode rounds block_k up.
    bq = min(block_q, _ceil_to(lq, 8))
    if interpret:
        bk = min(block_k, _ceil_to(lk, 8))
    else:
        bk = min(_ceil_to(block_k, _LANES), _ceil_to(lk, _LANES))
    lq_p, lk_p, d_p = _ceil_to(lq, bq), _ceil_to(lk, bk), _ceil_to(d, _LANES)

    def fold(t, l_p):  # [B, L, H, D] -> [B*H, L_pad, D_pad]
        t = jnp.pad(t, ((0, 0), (0, l_p - t.shape[1]), (0, 0),
                        (0, d_p - d)))
        return t.transpose(0, 2, 1, 3).reshape(b * h, l_p, t.shape[-1])

    qf, kf, vf = fold(q, lq_p), fold(k, lk_p), fold(v, lk_p)
    if kv_mask is None:
        mask = jnp.ones((b, lk), jnp.int32)
    else:
        mask = kv_mask.astype(jnp.int32)
    mask = jnp.pad(mask, ((0, 0), (0, lk_p - lk)))
    mask = jnp.broadcast_to(mask[:, None, :], (b, h, lk_p)).reshape(
        b * h, 1, lk_p)

    cfg = _Config(scale=float(scale), causal=bool(causal),
                  block_q=bq, block_k=bk, interpret=bool(interpret),
                  q_offset=int(q_offset))
    o = _flash(cfg, qf, kf, vf, mask)
    o = o.reshape(b, h, lq_p, d_p).transpose(0, 2, 1, 3)
    return o[:, :lq, :, :d]
