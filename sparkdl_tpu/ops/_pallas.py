"""Shared helpers for the Pallas kernel family (flash attention/decode,
fused GEMM+BN): scratch-space constructors and the interpret-mode default.
One definition so a convention change (e.g. an env override for interpret
mode) lands everywhere at once."""

from __future__ import annotations

import jax


def vmem(shape, dtype):
    from jax.experimental.pallas import tpu as pltpu

    return pltpu.VMEM(shape, dtype)


def smem(shape, dtype):
    from jax.experimental.pallas import tpu as pltpu

    return pltpu.SMEM(shape, dtype)


def smem_space():
    from jax.experimental.pallas import tpu as pltpu

    return pltpu.SMEM


def auto_interpret() -> bool:
    """Pallas interpreter mode anywhere that is not a real TPU backend
    (the CPU test harness and the virtual mesh)."""
    return jax.default_backend() != "tpu"
