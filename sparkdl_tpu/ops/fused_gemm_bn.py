"""Pallas TPU fused 1x1-conv GEMM with BatchNorm-training epilogues.

The training-MFU profile (PERF.md "Training MFU") shows ResNet50 training
is bandwidth-bound on BN-*training* passes: XLA materializes each conv
output to HBM, re-reads it to reduce batch statistics, and re-reads it
again to normalize — three full passes over 56²-stage activations that a
GPU reference hides behind cuDNN's fused BN kernels (SURVEY.md 2.18's
libtensorflow dispatch). A 1x1 conv in NHWC is exactly a GEMM
([N·H·W, Cin] @ [Cin, Cout]) — ~2/3 of ResNet50's conv layers — so this
kernel owns that GEMM and fuses the BN work into its memory traffic:

* **input epilogue** — the previous BN's normalize+ReLU is applied to x
  tiles after the VMEM load (``y = relu(scale·x + shift) @ w``), so
  normalized activations never exist in HBM;
* **stat epilogue** — per-channel ``Σy`` and ``Σy²`` accumulate across M
  tiles into a [2, Cout] output, so THIS layer's BN statistics cost no
  extra pass.

Per 1x1-conv layer that replaces (normalize pass + conv + stats pass)
with one kernel whose HBM traffic is read-x + read-w + write-y.

The custom VJP keeps the backward in plain jnp: both backward GEMMs take
elementwise-adjusted operands (``dY' = dy + dΣ + 2y·dΣ²``, recomputed
``a = relu(scale·x+shift)``) and XLA fuses those producers into the dot
reads, so no extra HBM pass materializes there either.

CPU (tests / virtual mesh): kernels run in Pallas interpreter mode
automatically, same convention as ops/flash_attention.py.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


@dataclasses.dataclass(frozen=True)
class _Config:
    """Static kernel configuration (hashable: custom_vjp nondiff arg)."""

    relu_in: bool
    has_affine: bool
    has_bias: bool
    block_m: int
    block_n: int
    block_k: int
    interpret: bool


from sparkdl_tpu.ops._pallas import auto_interpret as _auto_interpret
from sparkdl_tpu.ops._pallas import vmem as _vmem


def _ceil_to(x: int, m: int) -> int:
    return (x + m - 1) // m * m


# ---------------------------------------------------------------------------
# forward kernel
# ---------------------------------------------------------------------------


def _fwd_kernel(x_ref, w_ref, scale_ref, shift_ref, bias_ref,
                y_ref, stats_ref, acc_scr, *, cfg: _Config, m_true: int):
    """Grid (j, i, k): k innermost accumulates the GEMM in f32 scratch;
    for fixed j the i sweep revisits the [2, bn] stats block consecutively,
    so the epilogue accumulates partial channel sums in VMEM."""
    j, i, k = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(k == 0)
    def _init_acc():
        acc_scr[:] = jnp.zeros_like(acc_scr)

    x = x_ref[:]  # [bm, bk] storage dtype
    if cfg.has_affine:
        # previous layer's BN-normalize (+ReLU) fused into the load: the
        # f32 affine runs on the VPU against tiles already in VMEM
        a = x.astype(jnp.float32) * scale_ref[0] + shift_ref[0]
        if cfg.relu_in:
            a = jnp.maximum(a, 0.0)
        x = a.astype(x_ref.dtype)
    elif cfg.relu_in:
        x = jnp.maximum(x, 0)
    # operands stay bf16 into the MXU with f32 accumulate (PERF.md:
    # upcasting first forces 6-pass f32 matmuls)
    acc_scr[:] += jax.lax.dot_general(
        x, w_ref[:], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(k == nk - 1)
    def _epilogue():
        y = acc_scr[:]
        if cfg.has_bias:
            y = y + bias_ref[0]
        bm = y.shape[0]
        if m_true % bm != 0:
            # zero padded rows so they cannot pollute the channel stats
            rows = i * bm + jax.lax.broadcasted_iota(
                jnp.int32, y.shape, 0
            )
            y = jnp.where(rows < m_true, y, 0.0)
        y_ref[:] = y.astype(y_ref.dtype)
        part = jnp.stack(
            [jnp.sum(y, axis=0), jnp.sum(y * y, axis=0)]
        )  # [2, bn] f32

        @pl.when(i == 0)
        def _first():
            stats_ref[:] = part

        @pl.when(i != 0)
        def _rest():
            stats_ref[:] += part


def _fwd_call(x, w, scale, shift, bias, cfg: _Config):
    m, k_dim = x.shape
    n = w.shape[1]
    bm = min(cfg.block_m, _ceil_to(m, 16))
    bk = min(cfg.block_k, _ceil_to(k_dim, 128))
    bn = min(cfg.block_n, _ceil_to(n, 128))
    mp, kp, np_ = _ceil_to(m, bm), _ceil_to(k_dim, bk), _ceil_to(n, bn)
    xp = x
    if (mp, kp) != (m, k_dim):
        xp = jnp.pad(x, ((0, mp - m), (0, kp - k_dim)))
    wp = w
    if (kp, np_) != (k_dim, n):
        wp = jnp.pad(w, ((0, kp - k_dim), (0, np_ - n)))

    def pad1(v, size, fill=0.0):
        if v.shape[0] != size:
            v = jnp.pad(v, (0, size - v.shape[0]),
                        constant_values=fill)
        return v.reshape(1, size).astype(jnp.float32)

    # affine defaults keep padded-K lanes inert: scale 0 ⇒ padded columns
    # of x contribute shift only... so shift must also be 0 there; relu of
    # 0 is 0; padded x rows/cols are zero, so identity is safe too.
    scale2 = pad1(scale if scale is not None else jnp.ones(k_dim), kp)
    shift2 = pad1(shift if shift is not None else jnp.zeros(k_dim), kp)
    bias2 = pad1(bias if bias is not None else jnp.zeros(n), np_)

    grid = (np_ // bn, mp // bm, kp // bk)
    kernel = functools.partial(_fwd_kernel, cfg=cfg, m_true=m)
    y, stats = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, bk), lambda j, i, k: (i, k)),
            pl.BlockSpec((bk, bn), lambda j, i, k: (k, j)),
            pl.BlockSpec((1, bk), lambda j, i, k: (0, k)),
            pl.BlockSpec((1, bk), lambda j, i, k: (0, k)),
            pl.BlockSpec((1, bn), lambda j, i, k: (0, j)),
        ],
        out_specs=[
            pl.BlockSpec((bm, bn), lambda j, i, k: (i, j)),
            pl.BlockSpec((2, bn), lambda j, i, k: (0, j)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((mp, np_), x.dtype),
            jax.ShapeDtypeStruct((2, np_), jnp.float32),
        ],
        scratch_shapes=[_vmem((bm, bn), jnp.float32)],
        interpret=cfg.interpret,
    )(xp, wp, scale2, shift2, bias2)
    return y[:m, :n], stats[0, :n], stats[1, :n]


# ---------------------------------------------------------------------------
# public op with custom VJP
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def gemm_bn_stats(x, w, scale, shift, bias, cfg: _Config):
    """``y = act(scale·x + shift) @ w + bias`` plus per-channel (Σy, Σy²).

    ``act`` is ReLU when ``cfg.relu_in`` (the fused previous-BN epilogue);
    scale/shift/bias may be None per cfg flags. Returns (y, ysum, ysq).
    """
    return _fwd_call(x, w, scale, shift, bias, cfg)


def _fwd_rule(x, w, scale, shift, bias, cfg: _Config):
    y, ysum, ysq = _fwd_call(x, w, scale, shift, bias, cfg)
    return (y, ysum, ysq), (x, w, scale, shift, bias, y)


def _bwd_rule(cfg: _Config, res, grads):
    """Backward in the storage dtype: every [M, N]/[M, K]-sized
    intermediate that XLA must materialize (dY', dpre) is cast to
    ``x.dtype`` at its producer — f32 versions of these arrays measured
    as the dominant HBM sinks of the whole train step on chip. The tiny
    per-channel reductions still accumulate in f32."""
    x, w, scale, shift, bias, y = res
    dy, dsum, dsq = grads
    f32 = jnp.float32
    lp = x.dtype
    # stats cotangents fold into an adjusted dY'; XLA fuses this
    # elementwise producer into both backward GEMM reads
    dyp = (dy.astype(f32) + dsum.astype(f32)[None, :]
           + 2.0 * y.astype(f32) * dsq.astype(f32)[None, :]).astype(lp)

    if cfg.has_affine:
        pre = (x.astype(f32) * scale[None, :]
               + shift[None, :]).astype(lp)
        a = jnp.maximum(pre, 0) if cfg.relu_in else pre
    elif cfg.relu_in:
        a = jnp.maximum(x, 0)
    else:
        a = x

    dw = jax.lax.dot_general(
        a, dyp, (((0,), (0,)), ((), ())),
        preferred_element_type=f32,
    ).astype(w.dtype)
    dbias = (jnp.sum(dyp.astype(f32), axis=0).astype(bias.dtype)
             if bias is not None else None)

    da = jax.lax.dot_general(
        dyp, w, (((1,), (1,)), ((), ())),
        preferred_element_type=f32,
    ).astype(lp)
    if cfg.has_affine:
        dpre = (jnp.where(pre > 0, da, jnp.zeros((), lp))
                if cfg.relu_in else da)
        dscale = jnp.sum(dpre.astype(f32) * x.astype(f32),
                         axis=0).astype(scale.dtype)
        dshift = jnp.sum(dpre.astype(f32), axis=0).astype(shift.dtype)
        dx = (dpre * scale[None, :].astype(lp)).astype(x.dtype)
    else:
        dpre = (jnp.where(x > 0, da, jnp.zeros((), lp))
                if cfg.relu_in else da)
        dscale = dshift = None
        dx = dpre.astype(x.dtype)
    return dx, dw, dscale, dshift, dbias


gemm_bn_stats.defvjp(_fwd_rule, _bwd_rule)


# ---------------------------------------------------------------------------
# layer-level wrapper: 1x1 conv + BN-train statistics
# ---------------------------------------------------------------------------


def conv1x1_bn_stats(
    x, w, bias=None, *,
    prev_bn=None, relu_in: bool = False, stride: int = 1,
    block_m: int = 512, block_n: int = 256, block_k: int = 512,
    interpret: "bool | None" = None,
):
    """Fused NHWC 1x1 conv with BN-training epilogues.

    ``x``: [B, H, W, Cin] (RAW pre-normalize activation when ``prev_bn``
    is given). ``w``: [1, 1, Cin, Cout] or [Cin, Cout]. ``prev_bn`` =
    (mean, var, gamma, beta, eps) of the BN that normalizes x; its
    normalize (+ReLU when ``relu_in``) runs inside the kernel. Returns
    ``(y, batch_mean, batch_var)`` with y [B, H', W', Cout] and the
    biased batch moments this layer's BN needs (computed from the f32
    accumulator — one epilogue instead of a full HBM pass).
    """
    if w.ndim == 4:
        if w.shape[:2] != (1, 1):
            raise ValueError(f"not a 1x1 kernel: {w.shape}")
        w = w[0, 0]
    if stride != 1:
        x = x[:, ::stride, ::stride, :]
    b, h, wd, cin = x.shape
    scale = shift = None
    if prev_bn is not None:
        mean, var, gamma, beta, eps = prev_bn
        scale = (gamma * jax.lax.rsqrt(var + eps)).astype(jnp.float32)
        shift = (beta - mean * scale).astype(jnp.float32)
    cfg = _Config(
        relu_in=relu_in,
        has_affine=prev_bn is not None,
        has_bias=bias is not None,
        block_m=block_m, block_n=block_n, block_k=block_k,
        interpret=_auto_interpret() if interpret is None else interpret,
    )
    y, ysum, ysq = gemm_bn_stats(
        x.reshape(b * h * wd, cin), w, scale, shift, bias, cfg
    )
    m = b * h * wd
    mean_y = ysum / m
    var_y = jnp.maximum(ysq / m - mean_y * mean_y, 0.0)
    return y.reshape(b, h, wd, w.shape[1]), mean_y, var_y


def reference_conv1x1_bn_stats(x, w, bias=None, *, prev_bn=None,
                               relu_in=False, stride=1):
    """Plain-jnp oracle for the fused op (tests; also documents the math)."""
    if w.ndim == 4:
        w = w[0, 0]
    if stride != 1:
        x = x[:, ::stride, ::stride, :]
    a = x.astype(jnp.float32)
    if prev_bn is not None:
        mean, var, gamma, beta, eps = prev_bn
        scale = gamma * jax.lax.rsqrt(var + eps)
        a = a * scale[None, None, None, :] + (beta - mean * scale)
    if relu_in:
        a = jnp.maximum(a, 0.0)
    y = jax.lax.dot_general(
        a.astype(x.dtype), w, (((3,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    if bias is not None:
        y = y + bias
    m = y.shape[0] * y.shape[1] * y.shape[2]
    mean_y = jnp.sum(y, axis=(0, 1, 2)) / m
    var_y = jnp.maximum(
        jnp.sum(y * y, axis=(0, 1, 2)) / m - mean_y * mean_y, 0.0
    )
    return y.astype(x.dtype), mean_y, var_y
