"""Pallas TPU flash-decode: single-query KV-cache attention.

Closes VERDICT r2 missing #4: ``attn_impl`` now covers the decode path.
The dense cached step computes ``softmax(q·Kᵀ)·V`` through XLA with the
[B, H, 1, L] score tensor round-tripping HBM and five separate fusions;
this kernel streams the cache once — K/V blocks HBM→VMEM, online-softmax
running (max, denom) riding in scratch across the K-block grid — and
writes only the [D] context row.

Decode is bandwidth-bound (the whole KV cache is read per token), so the
math deliberately stays on the VPU: per block, scores are an elementwise
multiply + lane reduce ([bk, D] · [1, D] → [bk, 1]) and the context
update a sublane reduce — a [1, D] @ [D, bk] matvec would occupy one MXU
row and win nothing. Positions ``> idx`` (unwritten cache) are masked via
the scalar ``idx`` in SMEM.

Inference-only: no custom VJP (decode never backprops).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG_INF = -1e30


from sparkdl_tpu.ops._pallas import smem as _smem
from sparkdl_tpu.ops._pallas import smem_space as _smem_space
from sparkdl_tpu.ops._pallas import vmem as _vmem


def _kernel(idx_ref, start_ref, q_ref, k_ref, v_ref, o_ref,
            acc_scr, m_scr, l_scr, *, scale: float, bk: int, h: int):
    ki = pl.program_id(1)
    nk = pl.num_programs(1)

    @pl.when(ki == 0)
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr)
        m_scr[0, 0] = _NEG_INF
        l_scr[0, 0] = 0.0

    # this row's first valid position (left-padded prompts): grid dim 0 is
    # b*h, so the batch row is i // h
    start = start_ref[pl.program_id(0) // h]
    # positions strictly after idx are unwritten, before start are padding;
    # skip blocks entirely outside [start, idx]
    live = (ki * bk <= idx_ref[0]) & (ki * bk + bk > start)

    @pl.when(live)
    def _attend():
        q = q_ref[0]  # [1, D]
        k = k_ref[0]  # [bk, D]
        s = jnp.sum(
            k.astype(jnp.float32) * q.astype(jnp.float32), axis=-1,
            keepdims=True,
        ) * scale  # [bk, 1] f32
        pos = ki * bk + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 0
        )
        s = jnp.where((pos <= idx_ref[0]) & (pos >= start), s, _NEG_INF)

        m_prev = m_scr[0, 0]
        m_cur = jnp.max(s)
        m_next = jnp.maximum(m_prev, m_cur)
        corr = jnp.exp(m_prev - m_next)
        p = jnp.exp(s - m_next)  # [bk, 1]
        l_scr[0, 0] = l_scr[0, 0] * corr + jnp.sum(p)
        m_scr[0, 0] = m_next
        v = v_ref[0].astype(jnp.float32)  # [bk, D]
        acc_scr[:] = acc_scr[:] * corr + jnp.sum(
            p * v, axis=0, keepdims=True
        )

    @pl.when(ki == nk - 1)
    def _finish():
        o_ref[0] = (acc_scr[:] / l_scr[0, 0]).astype(o_ref.dtype)


def flash_decode(q, ck, cv, idx, *, start=None, block_k: int = 512,
                 interpret: "bool | None" = None):
    """One decode step of cached attention.

    q: [B, 1, H, D] (this step's query); ck/cv: [B, L, H, D] cache
    buffers with positions ``<= idx`` written (idx = this query's
    position, scalar int32). ``start`` ([B] int32, default 0) is each
    row's first VALID cache position — left-padded ragged prompts mask
    columns ``< start[b]`` out of the softmax. Returns ctx [B, 1, H, D]
    == ``softmax(q·K[start:idx+1]ᵀ/√D)·V[start:idx+1]``.
    """
    if interpret is None:
        from sparkdl_tpu.ops._pallas import auto_interpret

        interpret = auto_interpret()
    b, lq, h, d = q.shape
    if lq != 1:
        raise ValueError(f"flash_decode is single-query (got L={lq})")
    lmax = ck.shape[1]
    bk = min(block_k, lmax)
    if lmax % bk:
        bk = math.gcd(lmax, bk)
    if bk % 8 and bk != lmax:
        bk = lmax  # Mosaic: sublane block dim must be 8-divisible or full

    # rank-3 views with a singleton middle dim: Mosaic requires the last
    # two block dims to be (8-divisible | full); (1, d) blocks on a 2D
    # array violate that, (1, 1, d) blocks on [BH, 1, D] are legal
    qf = q.reshape(b, h, d).reshape(b * h, 1, d)
    # [B, L, H, D] -> [B*H, L, D]
    kf = ck.transpose(0, 2, 1, 3).reshape(b * h, lmax, d)
    vf = cv.transpose(0, 2, 1, 3).reshape(b * h, lmax, d)
    idx_arr = jnp.asarray(idx, jnp.int32).reshape(1)
    if start is None:
        start_arr = jnp.zeros((b,), jnp.int32)
    else:
        start_arr = jnp.asarray(start, jnp.int32).reshape(b)

    out = pl.pallas_call(
        functools.partial(_kernel, scale=1.0 / math.sqrt(d), bk=bk, h=h),
        grid=(b * h, lmax // bk),
        in_specs=[
            pl.BlockSpec(memory_space=_smem_space()),
            pl.BlockSpec(memory_space=_smem_space()),
            pl.BlockSpec((1, 1, d), lambda i, ki: (i, 0, 0)),
            pl.BlockSpec((1, bk, d), lambda i, ki: (i, ki, 0)),
            pl.BlockSpec((1, bk, d), lambda i, ki: (i, ki, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, d), lambda i, ki: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, 1, d), q.dtype),
        scratch_shapes=[
            _vmem((1, d), jnp.float32),
            _smem((1, 1), jnp.float32),
            _smem((1, 1), jnp.float32),
        ],
        interpret=interpret,
    )(idx_arr, start_arr, qf, kf, vf)
    return out.reshape(b, h, d).reshape(b, 1, h, d)


def reference_decode(q, ck, cv, idx, start=None):
    """Dense oracle (the pre-kernel cached path's math, single query)."""
    b, _, h, d = q.shape
    lmax = ck.shape[1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, ck,
                   preferred_element_type=jnp.float32) / math.sqrt(d)
    mask = jnp.arange(lmax)[None, None, None, :] <= idx
    if start is not None:
        mask = mask & (
            jnp.arange(lmax)[None, :] >= jnp.asarray(start)[:, None]
        )[:, None, None, :]
    s = jnp.where(mask, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", p, cv)
