"""Whole-stem Pallas kernel: InceptionV3 stem as ONE Mosaic program.

VERDICT r4 directive 1: the per-fusion ceiling table (PERF.md round 3)
localizes the recoverable inference time in the lane-starved stem —
conv1 u8 3→32 @149², two C≤64 3x3 convs @147², maxpool — ~2.2 ms of the
12.62 ms program at 30-74% efficiency, with every piecewise lever
(grouped convs, per-op Pallas islands) chip-measured dead. This kernel
is the named untried shape: the WHOLE stem as one program, layouts
internal, boundaries only at the u8 input and the small 73²×64 output,
so the Mosaic layout tax that killed per-op islands does not apply.

Design notes (why each choice):

- **One image per grid step.** The full intermediate chain for one image
  (~6.5 MB bf16) fits VMEM, so there is no halo exchange at all; Mosaic
  pipelining prefetches image b+1's DMA during image b's compute.
- **Flat [rows*W, C] layout + static slices.** All convs run on
  2-D row-major flattenings whose reshapes ([R, W, C] <-> [R*W, C],
  leading-dim splits) are layout-preserving in Mosaic. Shifted conv taps
  are STATIC slices of the flat array (the band carries its own halo
  rows); column wrap-around junk is confined to masked columns.
- **Row-pair packed GEMMs.** A plain im2col of a C=32 conv is
  [M, 288] @ [288, 32]: K fills the 128-lane contraction but N=32 uses a
  quarter of the MXU's output columns — the same starvation that caps
  XLA's stem fusions. Packing TWO output rows into the N dim
  (N = 2×C = 64/64/128 here, K = the 4-row tap union = 72/384/384)
  doubles PE utilization at a 1.33x MAC overhead: the only GEMM shape
  with a chance against XLA's spatial-packed conv lowering at C<=64.
- **Stride-2 conv1 via space-to-depth outside the kernel.** The u8
  [B,299,299,3] -> [B,150,150,12] rearrange is a cheap XLA byte shuffle
  (34 MB); it turns the strided conv into a stride-1 2x2 conv whose taps
  are plain slices.
- **BN + 'tf'-preprocess folded into weights/scale/bias** (inference
  stem: conv-BN-relu with use_scale=False, eps=1e-3 — models/common.py).
- **SAME padding and pooling via zero-masked junk columns**: keeping the
  full flat width through the chain means a roll past a row end lands in
  a zeroed junk column, which implements SAME padding exactly; the
  stride-2 pool picks even rows/columns with layout-preserving
  leading-dim reshape splits, never strided gathers.

Oracle: tests/ops/test_stem_fused.py (interpret mode, small + full
geometry) against the folded XLA stem; chip head-to-head in
tools/bench_stem.py, result recorded in PERF.md either way.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl


BAND = 16  # pool output rows per band (VMEM working-set knob)


# ---------------------------------------------------------------------------
# parameter folding / packing (numpy, oracle-testable)
# ---------------------------------------------------------------------------


def fold_stem_params(variables: dict, eps: float = 1e-3) -> dict:
    """Extract conv000-002 + bn000-002 and fold BN into (scale, bias).

    The zoo stem is bias-free conv + BatchNorm(use_scale=False), so
    y = relu(conv(x) * s + b) with s = 1/sqrt(var+eps), b = bias - mean*s.
    Works on plain or fold_tf_preprocess'ed variables (the fold only
    rescales conv000's kernel / shifts bn000's mean).
    """
    p, st = variables["params"], variables["batch_stats"]
    out = {}
    for i, name in enumerate(("000", "001", "002")):
        k = np.asarray(p[f"conv{name}"]["kernel"], np.float32)
        mean = np.asarray(st[f"bn{name}"]["mean"], np.float32)
        var = np.asarray(st[f"bn{name}"]["var"], np.float32)
        bias = np.asarray(p[f"bn{name}"]["bias"], np.float32)
        s = 1.0 / np.sqrt(var + eps)
        out[f"k{i + 1}"] = k
        out[f"s{i + 1}"] = s
        out[f"b{i + 1}"] = bias - mean * s
    return out


def _pack_pair_weights(k: np.ndarray, n_tap_rows: int,
                       row_of_tap) -> np.ndarray:
    """[kh,kw,ci,co] -> [n_tap_rows*kw*ci, 2*co] row-pair GEMM matrix.

    Row block (dy, dx, ci) feeds output block (p, co) with weight
    k[row_of_tap(dy, p), dx, ci, co] when that kernel row exists.
    """
    kh, kw, ci, co = k.shape
    b = np.zeros((n_tap_rows, kw, ci, 2, co), np.float32)
    for dy in range(n_tap_rows):
        for pp in range(2):
            ky = row_of_tap(dy, pp)
            if 0 <= ky < kh:
                b[dy, :, :, pp, :] = k[ky]
    return b.reshape(n_tap_rows * kw * ci, 2 * co)


def pack_stem_params(folded: dict) -> dict:
    """Fold -> the kernel's GEMM operands (see kernel layout contract)."""
    k1, k2, k3 = folded["k1"], folded["k2"], folded["k3"]
    # conv1 on space-to-depth cells: cell (cy, cx) phase (py, px) channel
    # c is original tap (2cy+py, 2cx+px, c); s2d channel = (py*2+px)*3+c.
    k1c = np.zeros((3, 2, 12, 32), np.float32)  # [cell_dy, cell_dx, cc, co]
    for cy in range(2):
        for cx in range(2):
            for py in range(2):
                for px in range(2):
                    ky, kx = 2 * cy + py, 2 * cx + px
                    if ky < 3 and kx < 3:
                        cc = (py * 2 + px) * 3
                        k1c[cy, cx, cc:cc + 3, :] = k1[ky, kx]
    # pair p of conv1 covers s2d cell rows (p + dy_rel): row_of_tap maps
    # tap row dy (0..2) to the kernel cell row dy - p (0..1)
    w1 = _pack_pair_weights(k1c, 3, lambda dy, pp: dy - pp)  # [72, 64]
    w2 = _pack_pair_weights(k2, 4, lambda dy, pp: dy - pp)  # [384, 64]
    w3 = _pack_pair_weights(k3, 4, lambda dy, pp: dy - pp)  # [384, 128]
    return {
        "w1": w1, "w2": w2, "w3": w3,
        "sb1": np.stack([np.tile(folded["s1"], 2),
                         np.tile(folded["b1"], 2)]),   # [2, 64]
        "sb2": np.stack([np.tile(folded["s2"], 2),
                         np.tile(folded["b2"], 2)]),   # [2, 64]
        "sb3": np.stack([np.tile(folded["s3"], 2),
                         np.tile(folded["b3"], 2)]),   # [2, 128]
    }


def space_to_depth(x_u8: jax.Array) -> jax.Array:
    """[B, S, S, 3] u8 -> [B, (S+1)//2, (S+1)//2, 12] u8 (XLA-side)."""
    b, s, _, c = x_u8.shape
    hs = (s + 1) // 2
    pad = 2 * hs - s
    x = jnp.pad(x_u8, ((0, 0), (0, pad), (0, pad), (0, 0)))
    x = x.reshape(b, hs, 2, hs, 2, c)
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(b, hs, hs, 12)


# ---------------------------------------------------------------------------
# kernel
# ---------------------------------------------------------------------------


def _band_plan(rp: int):
    """Static per-band row bookkeeping over pool output rows."""
    bands = []
    for u0 in range(0, rp, BAND):
        u1 = min(u0 + BAND, rp)
        np3 = (u1 - u0) + 1          # conv3 row pairs
        np2 = np3 + 2                # conv2 row pairs
        np1 = np2 + 2                # conv1 row pairs
        bands.append((u0, u1, np3, np2, np1))
    return bands


def _rows(flat, fw, n_rows, start, count):
    """[n_rows*fw, C] flat -> [count*fw, C] rows [start, start+count),
    zero-filled outside [0, n_rows). All-static concat of slices."""
    c = flat.shape[-1]
    pieces = []
    top = min(max(0, -start), count)
    if top:
        pieces.append(jnp.zeros((top * fw, c), flat.dtype))
    lo = min(max(start, 0), n_rows)
    hi = min(max(start + count, 0), n_rows)
    if hi > lo:
        pieces.append(flat[lo * fw:hi * fw])
    bot = count - top - (hi - lo)
    if bot:
        pieces.append(jnp.zeros((bot * fw, c), flat.dtype))
    return pieces[0] if len(pieces) == 1 else jnp.concatenate(pieces, 0)


def _split_even_odd(flat, fw, n_rows):
    """[n_rows*fw, C] (n_rows even) -> (even, odd) [n_rows//2*fw, C]."""
    c = flat.shape[-1]
    x = flat.reshape(n_rows // 2, 2, fw, c)
    return (x[:, 0].reshape(-1, c), x[:, 1].reshape(-1, c))


def _pair_gemm(src_flat, fw, n_src_rows, base, n_pairs, tap_rows, tap_cols,
               w, sb, out_dtype, col_shift: int = 0):
    """Row-pair conv GEMM.

    Pair p computes output rows (base+2p, base+2p+1) whose taps read
    src rows base+2p+dy (dy < tap_rows) and cols x+dx+col_shift
    (dx < tap_cols; col_shift=-1 gives a SAME conv's left column, with
    the out-of-range element zero-filled). Returns [n_pairs*fw, 2*co]
    = relu(A @ w * s + b).
    """
    # halo: dy//2 reaches n_pairs+ceil(tap_rows/2) rows per parity split
    half = -(-tap_rows // 2)
    need = 2 * (n_pairs + half)
    src = _rows(src_flat, fw, n_src_rows, base, need)
    ev, od = _split_even_odd(src, fw, need)
    parts = []
    m = n_pairs * fw
    c = src_flat.shape[-1]
    for dy in range(tap_rows):
        half_src = ev if dy % 2 == 0 else od
        row_off = dy // 2
        for dx in range(tap_cols):
            off = row_off * fw + dx + col_shift
            if off < 0:
                parts.append(jnp.concatenate(
                    [jnp.zeros((-off, c), src_flat.dtype),
                     half_src[:m + off]], axis=0))
            else:
                parts.append(half_src[off:off + m])
    a = jnp.concatenate(parts, axis=1)  # [m, tap_rows*tap_cols*ci]
    acc = jax.lax.dot_general(
        a, w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return jnp.maximum(acc * sb[0:1] + sb[1:2], 0.0).astype(out_dtype)


def _interleave_pairs(packed, fw, n_pairs, co):
    """[n_pairs*fw, 2*co] (lanes = (parity, ch)) -> [2*n_pairs*fw, co]."""
    ev = packed[:, :co].reshape(n_pairs, fw, co)
    od = packed[:, co:].reshape(n_pairs, fw, co)
    return jnp.stack([ev, od], axis=1).reshape(2 * n_pairs * fw, co)


def _zero_cols(flat, fw, n_rows, w_valid):
    """Zero columns >= w_valid of a [n_rows*fw, C] flat array."""
    if w_valid >= fw:
        return flat
    c = flat.shape[-1]
    x = flat.reshape(n_rows, fw, c)
    x = jnp.concatenate(
        [x[:, :w_valid], jnp.zeros((n_rows, fw - w_valid, c), flat.dtype)],
        axis=1,
    )
    return x.reshape(n_rows * fw, c)


def _stem_kernel(x_ref, w1_ref, w2_ref, w3_ref, sb1_ref, sb2_ref, sb3_ref,
                 o_ref, *, hs: int, rp: int, dtype):
    fw = hs
    r1 = hs - 1       # conv1 output rows (2x2 valid on s2d)
    r2 = r1 - 2       # conv2 output rows / conv3 (SAME) rows
    w2v = fw - 3      # valid columns after conv2 (and conv3)
    # Mosaic has no u8->float casts; widen to i32 first
    x = (x_ref[0].astype(jnp.int32).astype(jnp.float32)
         .astype(dtype).reshape(hs * hs, 12))
    w1 = w1_ref[...].astype(dtype)
    w2 = w2_ref[...].astype(dtype)
    w3 = w3_ref[...].astype(dtype)
    sb1, sb2, sb3 = sb1_ref[...], sb2_ref[...], sb3_ref[...]

    for u0, u1, np3, np2, np1 in _band_plan(rp):
        nb = u1 - u0
        g2 = 2 * u0 - 1   # conv2/out2 global start row (may be -1)
        # conv1: pairs over out1 rows starting at g2 (= conv2's input)
        out1 = _pair_gemm(x, fw, hs, g2, np1, 3, 2, w1, sb1, dtype)
        out1_i = _interleave_pairs(out1, fw, np1, 32)  # local rows g2+...
        # conv2 (valid 3x3): pair p -> out2 rows g2+2p, +1; taps read out1
        # local rows 2p+dy (local base 0 == global g2)
        out2 = _pair_gemm(out1_i, fw, 2 * np1, 0, np2, 4, 3, w2, sb2,
                          dtype)
        out2_i = _interleave_pairs(out2, fw, np2, 32)
        # SAME padding: junk cols AND out-of-range rows must read zero.
        # _pair_gemm zero-fills rows outside the local buffer, but rows
        # INSIDE the local buffer that are outside the image (global <0 or
        # >= r2) carry conv garbage -> zero them here (top band's row -1,
        # bottom band's rows >= r2).
        m2 = 2 * np2
        out2_i = _zero_cols(out2_i, fw, m2, w2v)
        kill_top = max(0, -g2)
        kill_bot = max(0, (g2 + m2) - r2)
        if kill_top or kill_bot:
            keep = m2 - kill_top - kill_bot
            z32 = functools.partial(jnp.zeros, dtype=dtype)
            out2_i = jnp.concatenate(
                ([z32((kill_top * fw, 32))] if kill_top else [])
                + [out2_i[kill_top * fw:(kill_top + keep) * fw]]
                + ([z32((kill_bot * fw, 32))] if kill_bot else []), 0)
        # conv3 (SAME 3x3): conv3 row R reads out2 global R-1..R+1 =
        # local (R - g2) - 1 + dy; pair p covers R = 2u0+2p, +1 ->
        # local tap base 2p (since 2u0 - g2 - 1 = 0). col_shift=-1 is
        # the SAME conv's left column (zero-filled / zeroed junk cols)
        out3 = _pair_gemm(out2_i, fw, m2, 0, np3, 4, 3, w3, sb3, dtype,
                          col_shift=-1)
        out3_i = _interleave_pairs(out3, fw, np3, 64)   # rows 2u0+...
        # maxpool 3x3 stride 2: stride-1 max via static shifts, then
        # even-row/even-col selection via leading-dim reshape splits
        m3 = 2 * np3
        # one zero tail row so the (dy=2, dx=2) shifted slice stays in
        # range (it only ever lands in discarded junk columns)
        out3_ext = jnp.concatenate(
            [out3_i, jnp.zeros((fw, 64), dtype)], axis=0)
        mx = None
        for dy in range(3):
            for dx in range(3):
                off = dy * fw + dx
                sl = out3_ext[off:off + (m3 - 2) * fw]
                mx = sl if mx is None else jnp.maximum(mx, sl)
        nr = m3 - 2                      # stride-1 pooled rows (even count)
        p3 = mx.reshape(nr // 2, 2, fw, 64)[:, 0]       # even rows [nb+?]
        p3 = p3[:nb]                                     # [nb, fw, 64]
        p3 = p3.reshape(nb, fw // 2, 2, 64)[:, :, 0]     # even cols
        o_ref[0, u0:u1] = p3[:, :rp].astype(o_ref.dtype)


def inception_stem_fused(x_u8: jax.Array, packed: dict, *,
                         dtype=jnp.bfloat16,
                         interpret: "bool | None" = None) -> jax.Array:
    """u8 [B, S, S, 3] images -> [B, Rp, Rp, 64] stem features.

    ``packed`` from :func:`pack_stem_params`. S odd (299 for the real
    model; any S with (S+1)//2 even works — tests use S=59).
    """
    if interpret is None:
        from sparkdl_tpu.ops._pallas import auto_interpret

        interpret = auto_interpret()
    b, s, _, _ = x_u8.shape
    hs = (s + 1) // 2
    if hs % 2:
        raise ValueError(f"stem needs even (S+1)//2, got S={s}")
    rp = ((hs - 3) - 3) // 2 + 1      # pool rows: ((hs-1-2) - 3)//2 + 1
    xs = space_to_depth(x_u8)

    to = lambda a, dt: jnp.asarray(a, dt)
    w1 = to(packed["w1"], dtype)
    w2 = to(packed["w2"], dtype)
    w3 = to(packed["w3"], dtype)
    sb1 = to(packed["sb1"], jnp.float32)
    sb2 = to(packed["sb2"], jnp.float32)
    sb3 = to(packed["sb3"], jnp.float32)

    rep = lambda shape: pl.BlockSpec(shape, lambda i: tuple(
        0 for _ in shape))
    out = pl.pallas_call(
        functools.partial(_stem_kernel, hs=hs, rp=rp, dtype=dtype),
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, hs, hs, 12), lambda i: (i, 0, 0, 0)),
            rep(w1.shape), rep(w2.shape), rep(w3.shape),
            rep(sb1.shape), rep(sb2.shape), rep(sb3.shape),
        ],
        out_specs=pl.BlockSpec((1, rp, rp, 64), lambda i: (i, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b, rp, rp, 64), dtype),
        interpret=interpret,
    )(xs, w1, w2, w3, sb1, sb2, sb3)
    return out


# ---------------------------------------------------------------------------
# XLA reference (oracle; also the head-to-head baseline on chip)
# ---------------------------------------------------------------------------


def stem_reference(x_u8: jax.Array, folded: dict,
                   dtype=jnp.float32) -> jax.Array:
    """The model's own stem math on the folded params (conv-BN-relu x3 +
    maxpool), via XLA convs — what the kernel must match and beat."""
    dn = ("NHWC", "HWIO", "NHWC")
    x = x_u8.astype(dtype)

    def cbr(x, k, s_, b_, strides, padding):
        y = jax.lax.conv_general_dilated(
            x, jnp.asarray(k, dtype), (strides, strides), padding,
            dimension_numbers=dn,
        )
        return jnp.maximum(y * jnp.asarray(s_, dtype)
                           + jnp.asarray(b_, dtype), 0.0)

    x = cbr(x, folded["k1"], folded["s1"], folded["b1"], 2, "VALID")
    x = cbr(x, folded["k2"], folded["s2"], folded["b2"], 1, "VALID")
    x = cbr(x, folded["k3"], folded["s3"], folded["b3"], 1, "SAME")
    return jax.lax.reduce_window(
        x, -jnp.inf, jax.lax.max, (1, 3, 3, 1), (1, 2, 2, 1), "VALID"
    )
