"""Algebraic folds that remove whole passes from the inference program.

``fold_tf_preprocess``: the 'tf' preprocessing mode (x/127.5 - 1, used by
InceptionV3/Xception — SURVEY.md 2.1's preprocessing registry) is an
affine map, and the stem is conv(VALID) -> BatchNorm, both linear in x. So
the preprocessing can be folded exactly into the stem weights:

    conv(x/127.5 - 1, W) = conv(x, W/127.5) - S,   S[o] = sum W[..., o]
    BN eval subtracts the running mean, so mean' = mean + S absorbs S.

(VALID padding matters: a constant input yields the same S at every output
position only when no zero padding enters the window.) After folding, the
jitted program consumes raw uint8-cast pixels directly — one full-image
elementwise pass (read 34 MB + write 68 MB per 128-batch at 299px) gone.
Measured on the v5e as part of the bench.py program (PERF.md).
"""

from __future__ import annotations

import jax.numpy as jnp


def fold_tf_preprocess(variables: dict, conv: str = "conv000",
                       bn: str = "bn000") -> dict:
    """Return new ``variables`` with 'tf'-mode preprocessing folded into
    the stem conv + BN. The model must then be fed RAW [0,255] pixels with
    the identity preprocessor.

    Asserted here: the stem conv is bias-free and the BN has a running
    mean. NOT checkable from ``variables`` alone (the caller must
    guarantee it): the stem conv uses VALID padding — with SAME padding
    the "-1" response is position-dependent at the borders and this fold
    is silently wrong. Both zoo 'tf'-mode stems (InceptionV3, Xception)
    are VALID.
    """
    params = variables["params"]
    stats = variables.get("batch_stats", {})
    if conv not in params or "kernel" not in params[conv]:
        raise ValueError(f"no stem conv {conv!r} in params")
    if "bias" in params[conv]:
        raise ValueError(
            f"stem conv {conv!r} has a bias; fold expects the zoo's "
            "bias-free conv+BN stem"
        )
    if bn not in stats or "mean" not in stats[bn]:
        raise ValueError(f"no running mean for {bn!r} in batch_stats")

    orig = params[conv]["kernel"]
    kernel = orig / 127.5
    # S[o]: the stem's response to the "-1" term rides the ORIGINAL
    # kernel scale — conv(x/127.5 - 1, W) = conv(x, W/127.5) - sum(W)
    shift = jnp.sum(orig, axis=(0, 1, 2))
    new_params = dict(params)
    new_params[conv] = dict(params[conv], kernel=kernel)
    new_stats = dict(stats)
    new_stats[bn] = dict(stats[bn], mean=stats[bn]["mean"] + shift)
    out = dict(variables)
    out["params"] = new_params
    out["batch_stats"] = new_stats
    return out
