"""Model input preprocessing, in JAX, fused into the jitted forward.

Parity with keras.applications preprocess_input modes used by the
reference's named-model registry (SURVEY.md 2.1): 'tf' (inception/xception),
'caffe' (resnet/vgg), 'torch'. Inputs are RGB float arrays in [0, 255] with
shape (..., H, W, 3); outputs are what each model family expects. Running
inside jit means preprocessing rides the same fusion as the model itself —
the reference spliced decode/resize *TF graph nodes* for the same reason
(SURVEY.md 2.10).
"""

from __future__ import annotations

import jax.numpy as jnp

_CAFFE_MEAN_BGR = (103.939, 116.779, 123.68)
_TORCH_MEAN = (0.485, 0.456, 0.406)
_TORCH_STD = (0.229, 0.224, 0.225)


def preprocess_tf(x: jnp.ndarray) -> jnp.ndarray:
    """Scale [0,255] -> [-1, 1]."""
    return x / 127.5 - 1.0


def preprocess_caffe(x: jnp.ndarray) -> jnp.ndarray:
    """RGB -> BGR, subtract ImageNet channel means (no scaling)."""
    x = x[..., ::-1]
    mean = jnp.asarray(_CAFFE_MEAN_BGR, dtype=x.dtype)
    return x - mean


def preprocess_torch(x: jnp.ndarray) -> jnp.ndarray:
    x = x / 255.0
    mean = jnp.asarray(_TORCH_MEAN, dtype=x.dtype)
    std = jnp.asarray(_TORCH_STD, dtype=x.dtype)
    return (x - mean) / std


def preprocess_identity(x: jnp.ndarray) -> jnp.ndarray:
    return x


PREPROCESSORS = {
    "tf": preprocess_tf,
    "caffe": preprocess_caffe,
    "torch": preprocess_torch,
    "identity": preprocess_identity,
}


def resize_images(x: jnp.ndarray, height: int, width: int,
                  method: str = "bilinear") -> jnp.ndarray:
    """Batched image resize on device (jax.image.resize, antialias off to
    match TF1-style resize the reference graphs used)."""
    import jax.image

    batch = x.shape[:-3]
    return jax.image.resize(
        x, (*batch, height, width, x.shape[-1]), method=method, antialias=False
    )
