"""ProbationBreaker: the shared quarantine/probation state machine.

ReplicaPool (ISSUE 5) and the fabric Router (ISSUE 14) grew the same
circuit breaker independently: ``max_failures`` *consecutive* failures
open the circuit (quarantine), after ``probation_s`` ONE live probe is
due, probe success closes the circuit, probe failure doubles the backoff
up to ``probation_max_s``. The two copies had already begun to drift in
spelling (the ROADMAP 1 follow-on named extracting them); this class is
the single implementation both consumers now hold — one transition rule
set, one place to fix it.

Deliberately NOT thread-safe: each consumer mutates its breakers under
its own lock (the pool lock / the router lock), exactly where the old
inline fields lived. The breaker carries no metrics or flight events
either — those are consumer-owned (``sparkdl_replica_*`` vs
``sparkdl_fabric_*`` families), so extraction changes no series.

Transition verbs:

* :meth:`record_failure` — one NON-probe failure; opens the circuit
  (returns True) when the consecutive-failure streak reaches
  ``max_failures``, scheduling the first probe ``probation_s`` out.
* :meth:`record_probe_failure` — a probation probe failed: stay open,
  double the backoff (capped at ``probation_max_s``), reschedule.
* :meth:`record_success` — any success: streak and backoff reset, an
  in-flight probe slot releases, and an open circuit closes (returns
  True — the consumer's "reintegrated" event/metric hook).
* :meth:`probe_due` / :meth:`begin_probe` / :meth:`release_probe` —
  probe scheduling: at most one probe in flight (``probing``);
  ``release_probe`` frees the slot on an *inconclusive* outcome (the
  probe's request failed for its own reasons, saying nothing about the
  host — without the release the circuit would never close).
* :meth:`trip` / :meth:`schedule_probe` — direct open (the hung-dispatch
  watchdog quarantines without a failure streak) and explicit probe
  (re)scheduling for consumers that gate probes on extra state (the
  pool's hung-freeze lifts by scheduling a probe one backoff out).

``probation_s=None`` disables probes entirely — an opened circuit stays
open (the pre-reliability permanent-quarantine behavior both consumers
still offer).
"""

from __future__ import annotations

import time

__all__ = ["ProbationBreaker"]


class ProbationBreaker:
    """One endpoint's circuit state (see module docstring). ``now`` is
    injectable everywhere (``time.monotonic`` default) so consumers can
    evaluate transitions at the single timestamp they read under their
    lock."""

    __slots__ = (
        "max_failures",
        "probation_s",
        "probation_max_s",
        "consecutive_failures",
        "quarantined",
        "probing",
        "probation_until",
        "probation_backoff_s",
    )

    def __init__(self, *, max_failures: int = 3,
                 probation_s: "float | None" = 1.0,
                 probation_max_s: float = 30.0):
        if max_failures < 1:
            raise ValueError(
                f"max_failures must be >= 1, got {max_failures}")
        if probation_s is not None and probation_s <= 0:
            raise ValueError(
                f"probation_s must be > 0 or None, got {probation_s}")
        if probation_max_s <= 0:
            raise ValueError(
                f"probation_max_s must be > 0, got {probation_max_s}")
        self.max_failures = max_failures
        self.probation_s = probation_s
        self.probation_max_s = probation_max_s
        self.consecutive_failures = 0
        self.quarantined = False
        self.probing = False
        #: monotonic time the next probation probe becomes due
        self.probation_until = 0.0
        self.probation_backoff_s = probation_s or 0.0

    # -- transitions ---------------------------------------------------------
    def record_success(self) -> bool:
        """Any successful unit of work: streak/backoff reset, probe slot
        released; returns True when this CLOSED an open circuit (the
        consumer fires its reintegration event/metric)."""
        self.consecutive_failures = 0
        self.probing = False
        if self.probation_s is not None:
            self.probation_backoff_s = self.probation_s
        if self.quarantined:
            self.quarantined = False
            return True
        return False

    def record_failure(self, now: "float | None" = None) -> bool:
        """One non-probe failure; returns True when the streak just
        opened the circuit (the consumer quarantines + emits)."""
        self.probing = False
        self.consecutive_failures += 1
        if (self.consecutive_failures >= self.max_failures
                and not self.quarantined):
            self.quarantined = True
            if self.probation_s is not None:
                self.probation_backoff_s = self.probation_s
                self.probation_until = (
                    (now if now is not None else time.monotonic())
                    + self.probation_s)
            return True
        return False

    def record_probe_failure(self, now: "float | None" = None) -> None:
        """A probation probe failed: stay open, back off exponentially
        (capped), schedule the next probe."""
        self.probing = False
        self.probation_backoff_s = min(
            self.probation_backoff_s * 2.0, self.probation_max_s)
        self.probation_until = (
            (now if now is not None else time.monotonic())
            + self.probation_backoff_s)

    def trip(self) -> bool:
        """Open the circuit directly, without a failure streak (the
        hung-dispatch watchdog's verb). Returns True when the circuit
        was previously closed (the consumer counts ONE quarantine)."""
        was_open = self.quarantined
        self.quarantined = True
        self.probing = False
        return not was_open

    # -- probe scheduling ----------------------------------------------------
    def probe_due(self, now: "float | None" = None) -> bool:
        """An open circuit whose backoff elapsed and no probe in flight:
        the next first-routing unit of work may probe it."""
        return (self.probation_s is not None and self.quarantined
                and not self.probing
                and (now if now is not None else time.monotonic())
                >= self.probation_until)

    def begin_probe(self) -> None:
        self.probing = True

    def release_probe(self) -> None:
        """Free the probe slot on an inconclusive outcome (the probe's
        request failed for its own reasons — deadline, bad payload —
        which says nothing about the endpoint)."""
        self.probing = False

    def schedule_probe(self, now: "float | None" = None) -> None:
        """(Re)schedule the next probe one current-backoff from ``now``
        (no-op with probes disabled)."""
        if self.probation_s is not None:
            self.probation_until = (
                (now if now is not None else time.monotonic())
                + self.probation_backoff_s)

    def next_probe_in_s(self, now: "float | None" = None
                        ) -> "float | None":
        """Seconds until the next probe is due (snapshot surface); None
        when closed or probes are disabled."""
        if not self.quarantined or self.probation_s is None:
            return None
        return max(0.0, self.probation_until
                   - (now if now is not None else time.monotonic()))
