"""Resumable training: crash → restore → replay → continue, exactly.

``finetune_classifier`` already checkpoints and already skips
already-trained steps on restart — what was missing is the *loop*: a
process that crashes (preemption, injected fault, transient device
error) simply died with its history. :func:`resumable_finetune` closes
the loop under a :class:`~sparkdl_tpu.reliability.retry.RetryPolicy`:

1. run an attempt; on a retryable failure, back off (full jitter);
2. the next attempt restores the newest *intact* checkpoint
   (``CheckpointManager.restore`` falls back past torn writes), replays
   the data iterator to the restored step, and continues;
3. per-step metrics are merged across attempts by step number — re-run
   steps (between the restored checkpoint and the crash point) recompute
   bitwise-identical values, so the recovered loss trajectory equals an
   uninterrupted run's exactly (pinned by tests and the run-tests.sh
   fault-injection smoke).

The barrier-retry resume story of SURVEY.md §5, productionized: what a
Spark stage retry does for a whole barrier job, this does in-process for
a single-host run.
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Iterable

from sparkdl_tpu.observability import flight
from sparkdl_tpu.reliability.retry import RetryPolicy

__all__ = ["resumable_finetune"]

_log = logging.getLogger(__name__)

#: Default classification for training crashes: retry anything except
#: clear programming errors — a preemption surfaces as RuntimeError /
#: OSError / a jax runtime error, all of which deserve a resume.
_DEFAULT_POLICY = dict(
    max_attempts=3,
    base_delay_s=0.05,
    max_delay_s=5.0,
    fatal=(TypeError, AssertionError),
)


def resumable_finetune(
    apply_fn: Callable[..., Any],
    params: Any,
    make_batches: "Callable[[], Iterable[dict]] | list[dict]",
    *,
    checkpoint_dir: str,
    retry: "RetryPolicy | None" = None,
    metrics_cb: "Callable[[dict], None] | None" = None,
    **finetune_kwargs: Any,
) -> "tuple[Any, list[dict]]":
    """``finetune_classifier`` that survives crashes mid-run.

    ``make_batches`` must be replayable: a zero-arg callable returning a
    fresh deterministic iterator (``lambda: batches_from_arrays(...)``)
    or a list of batches. A plain one-shot iterator cannot replay after
    a crash and is rejected loudly.

    ``checkpoint_dir`` is required — it is the recovery mechanism: each
    attempt resumes from the newest intact checkpoint in it (none on the
    first attempt = start from scratch). ``retry`` defaults to 3
    attempts with full-jitter backoff against the process retry budget.

    Returns ``(params, history)`` exactly like ``finetune_classifier``;
    ``history`` is merged across attempts by step, so it covers the full
    trajectory even though late attempts only run the tail. Re-run steps
    (restored checkpoint → crash point) recompute identical entries —
    recovery parity is bitwise, not approximate.
    """
    if not checkpoint_dir:
        raise ValueError(
            "resumable_finetune requires checkpoint_dir — the checkpoint "
            "IS the recovery mechanism"
        )
    if not callable(make_batches) and not isinstance(
            make_batches, (list, tuple)):
        raise TypeError(
            "make_batches must be a zero-arg callable returning a fresh "
            "iterator, or a list of batches — a one-shot iterator cannot "
            f"be replayed after a crash (got {type(make_batches).__name__})"
        )
    if retry is None:
        retry = RetryPolicy(**_DEFAULT_POLICY)

    from sparkdl_tpu.train.finetune import finetune_classifier

    #: step -> metrics entry, merged across attempts. Entries re-emitted
    #: by a replayed step overwrite with bitwise-identical values.
    entries: "dict[int, dict]" = {}

    def merge_cb(m: dict) -> None:
        entries[int(m["step"])] = m
        if metrics_cb is not None:
            metrics_cb(m)

    attempts = {"n": 0}

    def attempt():
        attempts["n"] += 1
        if attempts["n"] > 1:
            # the resume is a flight event: a postmortem shows the crash
            # -> restore -> replay chain, not just the final history
            flight.record_event(
                "supervisor.resume", attempt=attempts["n"],
                checkpoint_dir=str(checkpoint_dir),
                resumed_steps=len(entries),
            )
            _log.warning(
                "resumable_finetune: attempt %d resuming from %s",
                attempts["n"], checkpoint_dir,
            )
        batches = (make_batches() if callable(make_batches)
                   else make_batches)
        return finetune_classifier(
            apply_fn, params, batches,
            checkpoint_dir=checkpoint_dir,
            metrics_cb=merge_cb,
            **finetune_kwargs,
        )

    final_params, _ = retry.call(attempt, site="train.run")
    history = [entries[s] for s in sorted(entries)]
    return final_params, history
