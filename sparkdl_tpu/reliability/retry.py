"""Bounded retry with exponential backoff, full jitter, and a budget.

Until this module, every failure in the system was terminal on first
occurrence: a transient checkpoint-write error killed the run, a
momentary replica hiccup errored its riders. :class:`RetryPolicy` is the
one retry implementation every layer shares, so the semantics cannot
drift per call site:

* **Bounded attempts** — ``max_attempts`` total tries, never infinite.
* **Exponential backoff, full jitter** — attempt *n* sleeps a uniform
  draw from ``[0, min(max_delay, base * multiplier**(n-1))]`` (the AWS
  full-jitter scheme: decorrelates retry storms across processes and
  threads better than equal jitter at no extra cost).
* **Retryable vs fatal classification** — ``fatal`` types propagate
  immediately (programming errors must not burn retries); ``retryable``
  types retry; anything else propagates untouched.
* **Per-process retry budget** — a global token pool
  (``SPARKDL_TPU_RETRY_BUDGET``, default 256) caps total retries per
  process, so a persistent fault degrades to fail-fast instead of an
  unbounded retry storm amplifying the outage (the classic
  retry-budget argument from the SRE literature).
* **Observable** — each outcome lands in
  ``sparkdl_retries_total{site,outcome}`` (outcome ∈ retried /
  recovered / exhausted / budget / fatal) and every attempt runs under
  a ``retry.attempt`` span.

``sleep`` and ``seed`` are injectable so tests assert the exact backoff
sequence without wall-clock time.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import random
import threading
import time
from typing import Any, Callable

from sparkdl_tpu.observability import flight
from sparkdl_tpu.observability.registry import registry
from sparkdl_tpu.observability.tracing import span

__all__ = [
    "RetryBudget",
    "RetryExhaustedError",
    "RetryPolicy",
    "process_retry_budget",
    "record_retry",
]

_log = logging.getLogger(__name__)

_M_RETRIES = None


def _retries_counter():
    global _M_RETRIES
    if _M_RETRIES is None:
        _M_RETRIES = registry().counter(
            "sparkdl_retries_total",
            "retry outcomes per site (retried/recovered/exhausted/"
            "budget/fatal)",
            labels=("site", "outcome"))
    return _M_RETRIES


def record_retry(site: str, outcome: str) -> None:
    """Record one retry outcome into the spine — shared with callers
    that implement their own recovery loop (ReplicaPool re-routes,
    checkpoint fallback) so every second chance lands in ONE metric.
    Each outcome also lands in the flight ring: a postmortem shows the
    retry storm that preceded the trigger, not just its count."""
    _retries_counter().inc(site=site, outcome=outcome)
    flight.record_event("retry", site=site, outcome=outcome)


class RetryExhaustedError(RuntimeError):
    """All attempts failed (or the budget ran dry); ``__cause__`` holds
    the last underlying error."""


class RetryBudget:
    """Thread-safe token pool bounding total retries.

    Each retry consumes one token; success refunds nothing (the budget
    is a per-process lifetime cap, not a rate). ``reset()`` refills —
    test isolation and long-lived servers that want an epoch budget.
    """

    def __init__(self, tokens: int = 256):
        if tokens < 0:
            raise ValueError(f"tokens must be >= 0, got {tokens}")
        self.initial = tokens
        self._left = tokens
        self._lock = threading.Lock()

    def try_acquire(self) -> bool:
        with self._lock:
            if self._left <= 0:
                return False
            self._left -= 1
            return True

    @property
    def remaining(self) -> int:
        return self._left

    def reset(self, tokens: "int | None" = None) -> None:
        with self._lock:
            if tokens is not None:
                self.initial = tokens
            self._left = self.initial


_PROCESS_BUDGET: "RetryBudget | None" = None
_PROCESS_BUDGET_LOCK = threading.Lock()


def process_retry_budget() -> RetryBudget:
    """The per-process budget every default policy draws from
    (``SPARKDL_TPU_RETRY_BUDGET`` sets the size, default 256)."""
    global _PROCESS_BUDGET
    with _PROCESS_BUDGET_LOCK:
        if _PROCESS_BUDGET is None:
            _PROCESS_BUDGET = RetryBudget(
                int(os.environ.get("SPARKDL_TPU_RETRY_BUDGET", "256"))
            )
        return _PROCESS_BUDGET


@dataclasses.dataclass
class RetryPolicy:
    """The shared retry loop: ``policy.call(fn, site=...)``.

    ``retryable``/``fatal`` are exception-type tuples; fatal wins when
    both match (it is checked first), and exceptions matching neither
    propagate untouched — a retry policy must never convert a
    programming error into three programming errors and a sleep.
    """

    max_attempts: int = 3
    base_delay_s: float = 0.1
    max_delay_s: float = 30.0
    multiplier: float = 2.0
    retryable: "tuple[type, ...]" = (Exception,)
    fatal: "tuple[type, ...]" = ()
    #: None = the process-wide budget; pass a RetryBudget to isolate.
    budget: "RetryBudget | None" = None
    #: None = nondeterministic jitter; an int seeds it (tests).
    seed: "int | None" = None
    sleep: Callable[[float], None] = time.sleep

    def __post_init__(self):
        if self.max_attempts < 1:
            raise ValueError(
                f"max_attempts must be >= 1, got {self.max_attempts}"
            )

    def delay_s(self, attempt: int, rng: "random.Random") -> float:
        """Full-jitter backoff before attempt ``attempt + 1``."""
        ceiling = min(
            self.max_delay_s,
            self.base_delay_s * self.multiplier ** (attempt - 1),
        )
        return rng.uniform(0.0, ceiling)

    def call(self, fn: Callable[..., Any], *args: Any,
             site: str = "default", **kwargs: Any) -> Any:
        """Run ``fn(*args, **kwargs)`` under this policy.

        Raises :class:`RetryExhaustedError` (``__cause__`` = last error)
        when attempts or the budget run out; fatal and unclassified
        exceptions propagate as themselves immediately.
        """
        rng = random.Random(self.seed)
        budget = self.budget if self.budget is not None \
            else process_retry_budget()
        for attempt in range(1, self.max_attempts + 1):
            try:
                with span("retry.attempt", site=site, attempt=attempt):
                    out = fn(*args, **kwargs)
            except self.fatal:
                record_retry(site, "fatal")
                raise
            except self.retryable as e:
                if attempt >= self.max_attempts:
                    record_retry(site, "exhausted")
                    raise RetryExhaustedError(
                        f"{site}: all {self.max_attempts} attempts "
                        f"failed; last error: {e!r}"
                    ) from e
                if not budget.try_acquire():
                    record_retry(site, "budget")
                    raise RetryExhaustedError(
                        f"{site}: process retry budget exhausted "
                        f"(SPARKDL_TPU_RETRY_BUDGET) after attempt "
                        f"{attempt}; last error: {e!r}"
                    ) from e
                record_retry(site, "retried")
                delay = self.delay_s(attempt, rng)
                _log.warning(
                    "%s: attempt %d/%d failed (%r); retrying in %.3fs",
                    site, attempt, self.max_attempts, e, delay,
                )
                if delay > 0:
                    self.sleep(delay)
            else:
                if attempt > 1:
                    record_retry(site, "recovered")
                return out
        raise AssertionError("unreachable")  # pragma: no cover
