"""Reliability: deterministic fault injection, retry/backoff, recovery.

The ROADMAP's north star is serving heavy production traffic, and the
TensorFlow system paper (PAPERS.md) treats fault tolerance as a design
axis co-equal with performance — yet until this package a single thrown
exception ended a finetune run, a quarantined replica was dead forever,
and no failure path could be tested deterministically. Three pillars:

* :mod:`~sparkdl_tpu.reliability.faults` — a deterministic
  fault-injection harness: a :class:`FaultPlan` (from code or the
  ``SPARKDL_TPU_FAULT_PLAN`` env var) arms named sites — ``dispatch``,
  ``fetch``, ``replica.execute``, ``checkpoint.save``, ``worker.rank``
  — to raise a chosen exception on the Nth hit or with a seeded
  probability. Every production hot path carries a
  :func:`fault_point` that costs one global load when disarmed.
* :mod:`~sparkdl_tpu.reliability.retry` — :class:`RetryPolicy`:
  bounded attempts, exponential backoff with full jitter, a per-process
  retry budget, retryable-vs-fatal classification, and
  ``sparkdl_retries_total{site,outcome}`` metrics + ``retry.attempt``
  spans in the observability spine.
* :mod:`~sparkdl_tpu.reliability.supervisor` —
  :func:`resumable_finetune`: a crash (real or injected) mid-finetune
  restores the latest intact checkpoint, replays the data iterator to
  the restored step, and continues under the retry policy — the
  recovered per-step loss trajectory is bitwise-identical to an
  uninterrupted run.

The serving side builds on the same pieces: ``ReplicaPool`` quarantine
is a circuit breaker (probation probes with backoff, rejoin on
success), a micro-batch whose replica dies is re-routed once before its
riders see an error, and a hung dispatch is failed on a deadline
instead of wedging the pool (:mod:`sparkdl_tpu.serving.replicas`).
The circuit breaker itself is :mod:`~sparkdl_tpu.reliability.breaker`'s
:class:`ProbationBreaker` — ONE quarantine/probation/probe/backoff
state machine shared by ReplicaPool and the fabric Router (ISSUE 15),
so a transition fix propagates to both consumers.
"""

from sparkdl_tpu.reliability.breaker import ProbationBreaker
from sparkdl_tpu.reliability.faults import (
    FaultPlan,
    FaultRule,
    active_plan,
    arm,
    disarm,
    fault_point,
    inject,
)
from sparkdl_tpu.reliability.retry import (
    RetryBudget,
    RetryExhaustedError,
    RetryPolicy,
    process_retry_budget,
    record_retry,
)
from sparkdl_tpu.reliability.supervisor import resumable_finetune

__all__ = [
    "FaultPlan",
    "FaultRule",
    "ProbationBreaker",
    "RetryBudget",
    "RetryExhaustedError",
    "RetryPolicy",
    "active_plan",
    "arm",
    "disarm",
    "fault_point",
    "inject",
    "process_retry_budget",
    "record_retry",
    "resumable_finetune",
]
