"""Deterministic fault injection: armed sites that raise on demand.

Every robustness mechanism in this framework — retry, checkpoint
fallback, replica probation, the resumable-finetune supervisor — exists
to survive failures that are by nature rare and unrepeatable. This
module makes them repeatable: a :class:`FaultPlan` arms named *sites*
(``dispatch``, ``fetch``, ``replica.execute``, ``checkpoint.save``,
``worker.rank``) to raise a chosen exception on the Nth hit of the site
or with a seeded probability, and each production hot path carries a
:func:`fault_point` call that consults the armed plan.

Contracts:

* **Zero cost disarmed.** With no plan armed, :func:`fault_point` is a
  module-global load, an ``is None`` test, and a return — measured
  ~60 ns on the CPU harness (PERF.md), invisible next to a device
  dispatch. CI bench-guards this (run-tests.sh).
* **Deterministic.** ``@N`` rules count hits process-wide per site under
  a lock; ``%p`` rules draw from one seeded ``random.Random``. The same
  plan against the same execution order injects the same faults — the
  chaos soak and the recovery-parity tests depend on it.
* **Observable.** Every injected fault lands in the metrics spine as
  ``sparkdl_faults_injected_total{site=...}``.

Plan syntax (``SPARKDL_TPU_FAULT_PLAN`` or :meth:`FaultPlan.parse`) —
``;``-separated entries::

    seed=42                      # plan seed for %p rules
    dispatch@3                   # RuntimeError on the 3rd hit of site
    dispatch:OSError@3           # a chosen exception type (builtins)
    replica.execute:OSError@5*4  # hits 5,6,7,8 (4 injections from 5)
    checkpoint.save@2*           # every hit from the 2nd on
    fetch:TimeoutError%0.05      # each hit fails with probability 0.05

Subprocess workers inherit the plan through the environment: the module
parses ``SPARKDL_TPU_FAULT_PLAN`` once at import, so a
``LocalProcessBackend`` child (``worker.rank`` site) arms itself with no
plumbing. In-process tests use :func:`inject`/:func:`arm` instead —
changing the env var after import deliberately has no effect.
"""

from __future__ import annotations

import builtins
import contextlib
import dataclasses
import os
import random
import threading
from typing import Iterator

from sparkdl_tpu.observability import flight
from sparkdl_tpu.observability.registry import registry

__all__ = [
    "ENV_VAR",
    "FaultPlan",
    "FaultRule",
    "active_plan",
    "arm",
    "disarm",
    "fault_point",
    "inject",
]

ENV_VAR = "SPARKDL_TPU_FAULT_PLAN"

#: The sites production code arms today (informational — plans may name
#: new sites freely; a rule for a site nothing hits simply never fires).
KNOWN_SITES = (
    "dispatch",
    "fetch",
    "replica.execute",
    "checkpoint.save",
    "kv.alloc",
    "kv.quantize",
    "kv_pool.resize",
    "autoscale.decide",
    "replica.scale_down",
    "spec.verify",
    "sp.permute",
    "sp.gather",
    "router.route",
    "host.submit",
    "host.drain",
    "handoff.export",
    "handoff.install",
    "worker.rank",
    "kv.park",
    "kv.unpark",
    "digest.delta",
    "kv.migrate",
    "tenant.preempt",
)

_M_INJECTED = None


def _injected_counter():
    global _M_INJECTED
    if _M_INJECTED is None:
        _M_INJECTED = registry().counter(
            "sparkdl_faults_injected_total",
            "faults raised by the injection harness", labels=("site",))
    return _M_INJECTED


def _resolve_exception(name: str) -> type:
    exc = getattr(builtins, name, None)
    if isinstance(exc, type) and issubclass(exc, BaseException):
        return exc
    raise ValueError(
        f"unknown exception type {name!r} in fault plan (must be a "
        "builtin exception name, e.g. RuntimeError, OSError, TimeoutError)"
    )


@dataclasses.dataclass
class FaultRule:
    """One armed site: raise ``exc_type`` per the trigger below.

    ``on_hit``/``times`` is the deterministic trigger — inject on hits
    ``on_hit .. on_hit+times-1`` (``times=None`` = every hit from
    ``on_hit`` on). ``p`` is the probabilistic trigger (seeded by the
    plan). Exactly one of the two is active.
    """

    site: str
    exc_type: type = RuntimeError
    on_hit: "int | None" = None
    times: "int | None" = 1
    p: "float | None" = None
    message: str = ""
    injected: int = 0  # injections so far (plan-lock protected)

    def __post_init__(self):
        if (self.on_hit is None) == (self.p is None):
            raise ValueError(
                f"rule for {self.site!r}: exactly one of on_hit (@N) or "
                f"p (%p) must be set"
            )
        if self.on_hit is not None and self.on_hit < 1:
            raise ValueError(f"on_hit is 1-based, got {self.on_hit}")
        if self.times is not None and self.times < 1:
            raise ValueError(f"times must be >= 1 or None, got {self.times}")
        if self.p is not None and not (0.0 < self.p <= 1.0):
            raise ValueError(f"p must be in (0, 1], got {self.p}")

    def _should_fire(self, hit: int, rng: random.Random) -> bool:
        if self.on_hit is not None:
            if hit < self.on_hit:
                return False
            return self.times is None or self.injected < self.times
        return rng.random() < self.p

    def _make(self, hit: int) -> BaseException:
        detail = f": {self.message}" if self.message else ""
        return self.exc_type(
            f"injected fault at site {self.site!r} (hit {hit}){detail}"
        )

    @classmethod
    def parse(cls, entry: str) -> "FaultRule":
        """Parse one plan entry: ``site[:Exc][@N[*M|*]][%p]``."""
        text = entry.strip()
        p = None
        on_hit, times = None, 1
        if "%" in text:
            text, _, p_s = text.partition("%")
            try:
                p = float(p_s)
            except ValueError:
                raise ValueError(f"bad probability in fault rule {entry!r}")
        if "@" in text:
            text, _, hit_s = text.partition("@")
            if "*" in hit_s:
                hit_s, _, times_s = hit_s.partition("*")
                times = int(times_s) if times_s else None  # "@N*" = forever
            try:
                on_hit = int(hit_s)
            except ValueError:
                raise ValueError(f"bad hit number in fault rule {entry!r}")
        exc_type = RuntimeError
        if ":" in text:
            text, _, exc_name = text.partition(":")
            exc_type = _resolve_exception(exc_name.strip())
        site = text.strip()
        if not site:
            raise ValueError(f"fault rule {entry!r} names no site")
        if on_hit is None and p is None:
            on_hit = 1  # bare "site" / "site:Exc": first hit
        return cls(site=site, exc_type=exc_type, on_hit=on_hit,
                   times=times, p=p)


class FaultPlan:
    """A set of :class:`FaultRule` plus per-site hit counters.

    Build in code (``FaultPlan([FaultRule("dispatch", on_hit=3)])`` or
    ``FaultPlan.parse("dispatch@3")``) and activate with :func:`arm` /
    :func:`inject`. Thread-safe: sites are hit from serving worker
    threads and the training loop alike.
    """

    def __init__(self, rules: "list[FaultRule] | None" = None, *,
                 seed: int = 0):
        self.seed = seed
        self.rules: "list[FaultRule]" = list(rules or ())
        self._by_site: "dict[str, list[FaultRule]]" = {}
        for r in self.rules:
            self._by_site.setdefault(r.site, []).append(r)
        self._rng = random.Random(seed)
        self._hits: "dict[str, int]" = {}
        self._lock = threading.Lock()

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        """Parse a full ``;``-separated plan string (see module doc)."""
        rules: "list[FaultRule]" = []
        seed = 0
        for entry in spec.split(";"):
            entry = entry.strip()
            if not entry:
                continue
            if entry.startswith("seed="):
                seed = int(entry[len("seed="):])
                continue
            rules.append(FaultRule.parse(entry))
        if not rules:
            raise ValueError(f"fault plan {spec!r} contains no rules")
        return cls(rules, seed=seed)

    @classmethod
    def from_env(cls) -> "FaultPlan | None":
        spec = os.environ.get(ENV_VAR, "").strip()
        return cls.parse(spec) if spec else None

    def hit(self, site: str) -> None:
        """Count one hit of ``site``; raise if an armed rule fires."""
        rules = self._by_site.get(site)
        if rules is None:
            return
        with self._lock:
            n = self._hits[site] = self._hits.get(site, 0) + 1
            fire = None
            for rule in rules:
                if rule._should_fire(n, self._rng):
                    rule.injected += 1
                    fire = rule
                    break
        if fire is not None:
            _injected_counter().inc(site=site)
            # flight ring first (ISSUE 9): a postmortem triggered by the
            # failure this injection causes must contain its cause
            flight.record_event(
                "fault.injected", site=site, hit=n,
                error=fire.exc_type.__name__,
            )
            raise fire._make(n)

    def snapshot(self) -> dict:
        """Hit/injection counts per site (test/debug introspection)."""
        with self._lock:
            return {
                "hits": dict(self._hits),
                "injected": {
                    r.site: sum(
                        x.injected for x in self._by_site[r.site]
                    )
                    for r in self.rules
                },
            }


#: The armed plan. One module-global so the disarmed fault_point path is
#: a load + None-test; parsed from the environment once at import so
#: subprocess ranks inherit the parent's plan with no plumbing.
_ACTIVE: "FaultPlan | None" = FaultPlan.from_env()


def fault_point(site: str) -> None:
    """Hit the named fault site — raises iff an armed rule fires.

    This sits on every production hot path; keep the disarmed cost at
    one global load (bench-guarded in run-tests.sh and PERF.md).
    """
    plan = _ACTIVE
    if plan is not None:
        plan.hit(site)


def active_plan() -> "FaultPlan | None":
    return _ACTIVE


def arm(plan: "FaultPlan | str") -> FaultPlan:
    """Activate ``plan`` (a :class:`FaultPlan` or a plan string)."""
    global _ACTIVE
    if isinstance(plan, str):
        plan = FaultPlan.parse(plan)
    _ACTIVE = plan
    return plan


def disarm() -> None:
    global _ACTIVE
    _ACTIVE = None


@contextlib.contextmanager
def inject(plan: "FaultPlan | str") -> Iterator[FaultPlan]:
    """Arm ``plan`` for the body, restoring the previous plan after —
    the test/chaos-harness form (exception-safe)."""
    global _ACTIVE
    prev = _ACTIVE
    armed = arm(plan)
    try:
        yield armed
    finally:
        _ACTIVE = prev
