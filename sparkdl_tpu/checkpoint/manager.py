"""Async sharded checkpointing on Orbax (TPU-native resume story).

Design (SURVEY.md §5, §7 L-aux): every save is asynchronous — the host
snapshot is taken synchronously (cheap), the serialization/write happens on
a background thread while the next train steps run; ``wait()``/``close()``
drains. Multi-host coordination, atomicity (tmp dir + rename) and garbage
collection of old steps are Orbax's job; this module pins the framework's
conventions on top:

* one item named ``state`` holding the whole train-state pytree;
* restore-with-shardings: the caller passes a template pytree (e.g. the
  freshly initialized, device-put train state) and gets the checkpoint back
  with each leaf materialized on the template leaf's sharding — resume
  drops straight back into the same mesh;
* ``keep`` bounds disk usage (old steps GC'd).
"""

from __future__ import annotations

import os
import time
from typing import Any

import jax

from sparkdl_tpu.observability.registry import registry
from sparkdl_tpu.observability.tracing import record_span, span

_M_SAVES = registry().counter(
    "sparkdl_checkpoint_saves_total", "checkpoint saves queued")
_M_RESTORES = registry().counter(
    "sparkdl_checkpoint_restores_total", "checkpoint restores")
_M_SAVE_TIME = registry().histogram(
    "sparkdl_checkpoint_save_seconds",
    "synchronous (host-snapshot) part of an async save")
_M_RESTORE_TIME = registry().histogram(
    "sparkdl_checkpoint_restore_seconds", "restore wall time")
_M_WAIT_TIME = registry().histogram(
    "sparkdl_checkpoint_wait_seconds",
    "time blocked draining queued async saves")


def _abstract_like(tree: Any):
    """Template pytree -> abstract (shape/dtype/sharding) restore target."""

    def leaf(x):
        if isinstance(x, jax.Array):
            return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
        return x  # scalars / python leaves restore as saved

    return jax.tree_util.tree_map(leaf, tree)


class CheckpointManager:
    """Thin framework wrapper over ``orbax.checkpoint.CheckpointManager``.

    >>> ckpt = CheckpointManager(dir, keep=3)
    >>> ckpt.save(step, state)            # async; returns immediately
    >>> state = ckpt.restore(template=state)   # latest step, same shardings
    >>> ckpt.close()
    """

    def __init__(self, directory: str | os.PathLike, *, keep: int = 3,
                 save_interval_steps: int = 1):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.directory = os.fspath(directory)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=keep,
                save_interval_steps=save_interval_steps,
                enable_async_checkpointing=True,
            ),
        )

    # -- save ----------------------------------------------------------------
    def save(self, step: int, state: Any, *, force: bool = False) -> bool:
        """Queue an async save of ``state`` at ``step``.

        Returns False when the manager's save_interval policy skipped it
        (``force=True`` bypasses the policy — used for the final step).
        """
        # span + metrics only for saves that actually happen: the interval
        # policy skips most calls, and ~0s skip spans would pollute the
        # checkpoint.save stage percentiles (monotonic clock: record_span
        # and Request timestamps share time.monotonic)
        t0 = time.monotonic()
        saved = self._mgr.save(
            int(step), args=self._ocp.args.StandardSave(state), force=force
        )
        if saved:
            _M_SAVES.inc()
            _M_SAVE_TIME.observe(time.monotonic() - t0)
            record_span("checkpoint.save", t0, time.monotonic(),
                        step=int(step))
        return saved

    def wait(self) -> None:
        """Block until every queued async save has landed on disk."""
        t0 = time.perf_counter()
        self._mgr.wait_until_finished()
        _M_WAIT_TIME.observe(time.perf_counter() - t0)

    # -- restore -------------------------------------------------------------
    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def all_steps(self) -> list[int]:
        return sorted(self._mgr.all_steps())

    def restore(self, step: int | None = None, *, template: Any) -> Any:
        """Restore ``step`` (default: latest) shaped/sharded like ``template``.

        Each ``jax.Array`` leaf of the template contributes its sharding, so
        the restored state lands distributed across the same mesh it was
        initialized for — no host-memory spike, no manual device_put.
        """
        if step is None:
            step = self._mgr.latest_step()
        if step is None:
            raise FileNotFoundError(
                f"no checkpoint found under {self.directory}"
            )
        t0 = time.perf_counter()
        with span("checkpoint.restore", step=int(step)):
            out = self._mgr.restore(
                int(step),
                args=self._ocp.args.StandardRestore(_abstract_like(template)),
            )
        _M_RESTORES.inc()
        _M_RESTORE_TIME.observe(time.perf_counter() - t0)
        return out

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._mgr.close()

    def __enter__(self) -> "CheckpointManager":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# -- module-level conveniences (single-shot paths) ---------------------------

def save_and_wait(directory: str | os.PathLike, step: int, state: Any) -> None:
    """Synchronous one-shot save (estimator/model export paths)."""
    with CheckpointManager(directory) as mgr:
        mgr.save(step, state)


def latest_step(directory: str | os.PathLike) -> int | None:
    import orbax.checkpoint as ocp

    if not os.path.isdir(directory):
        return None
    with ocp.CheckpointManager(os.fspath(directory)) as mgr:
        return mgr.latest_step()


def restore_matching(directory: str | os.PathLike, template: Any,
                     step: int | None = None) -> Any:
    """One-shot restore shaped/sharded like ``template``."""
    with CheckpointManager(directory) as mgr:
        return mgr.restore(step, template=template)
