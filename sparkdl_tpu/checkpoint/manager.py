"""Async sharded checkpointing on Orbax (TPU-native resume story).

Design (SURVEY.md §5, §7 L-aux): every save is asynchronous — the host
snapshot is taken synchronously (cheap), the serialization/write happens on
a background thread while the next train steps run; ``wait()``/``close()``
drains. Multi-host coordination, atomicity (tmp dir + rename) and garbage
collection of old steps are Orbax's job; this module pins the framework's
conventions on top:

* one item named ``state`` holding the whole train-state pytree;
* restore-with-shardings: the caller passes a template pytree (e.g. the
  freshly initialized, device-put train state) and gets the checkpoint back
  with each leaf materialized on the template leaf's sharding — resume
  drops straight back into the same mesh;
* ``keep`` bounds disk usage (old steps GC'd).

Integrity (reliability layer): rename atomicity protects against a crash
*during* a save, but not against after-the-fact corruption — a truncated
file on a recycled disk, a bad copy, a bit flip — which previously
poisoned every future restore of that directory. Each landed save now
gets a content digest recorded in a sidecar manifest
(``sparkdl_integrity.json``); :meth:`CheckpointManager.restore` verifies
the chosen step against it and **falls back to the newest intact step**
when the newest one is torn (``sparkdl_checkpoint_corrupt_total`` /
``sparkdl_checkpoint_fallbacks_total`` count it). The synchronous
queueing part of :meth:`~CheckpointManager.save` additionally runs under
a small :class:`~sparkdl_tpu.reliability.retry.RetryPolicy` so a
transient filesystem error does not kill a training run.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import time
from typing import Any

import jax

from sparkdl_tpu.observability import flight
from sparkdl_tpu.observability.registry import registry
from sparkdl_tpu.observability.tracing import record_span, span
from sparkdl_tpu.reliability.faults import fault_point
from sparkdl_tpu.reliability.retry import RetryPolicy

_log = logging.getLogger(__name__)

_M_SAVES = registry().counter(
    "sparkdl_checkpoint_saves_total", "checkpoint saves queued")
_M_RESTORES = registry().counter(
    "sparkdl_checkpoint_restores_total", "checkpoint restores")
_M_SAVE_TIME = registry().histogram(
    "sparkdl_checkpoint_save_seconds",
    "synchronous (host-snapshot) part of an async save")
_M_RESTORE_TIME = registry().histogram(
    "sparkdl_checkpoint_restore_seconds", "restore wall time")
_M_WAIT_TIME = registry().histogram(
    "sparkdl_checkpoint_wait_seconds",
    "time blocked draining queued async saves")
_M_CORRUPT = registry().counter(
    "sparkdl_checkpoint_corrupt_total",
    "checkpoints that failed integrity verification")
_M_FALLBACKS = registry().counter(
    "sparkdl_checkpoint_fallbacks_total",
    "restores that fell back past a corrupt newest step")

#: Sidecar manifest (NOT inside any step dir, so Orbax GC never eats it).
MANIFEST_NAME = "sparkdl_integrity.json"


class CheckpointCorruptError(RuntimeError):
    """The requested checkpoint failed integrity verification (and, for
    latest-step restores, so did every older candidate)."""


def _integrity_verdict(verdict: str, *, step: "int | None" = None,
                       directory: "str | None" = None,
                       pinned: bool = False) -> None:
    """Publish the restore-side integrity verdict for ``/healthz`` and
    postmortems. ``intact`` / ``fallback`` / ``unreadable`` (every
    candidate failed but with NO digest mismatch — possibly the
    caller's template, so it only degrades health) / ``corrupt``
    (digest-verified damage; drives unhealthy unless ``pinned`` — a
    pinned-step failure says nothing about the newer history). The fact
    is a latch until the next successful restore publishes ``intact``/
    ``fallback``."""
    flight.set_health_fact("checkpoint_integrity", {
        "verdict": verdict,
        "step": step,
        "directory": directory,
        "pinned": pinned,
        "time_unix": time.time(),
    })


def checkpoint_digest(step_dir: str) -> dict:
    """Content digest of one landed step directory.

    sha256 over (sorted relative path, file bytes) pairs — any
    truncation, missing file, or flipped byte changes it. Sizes/count
    ride along for cheap debugging of a mismatch.
    """
    h = hashlib.sha256()
    n_files = 0
    n_bytes = 0
    for root, dirs, files in os.walk(step_dir):
        dirs.sort()  # in-place: pins the walk's traversal order
        for name in sorted(files):
            path = os.path.join(root, name)
            rel = os.path.relpath(path, step_dir)
            h.update(rel.encode())
            h.update(b"\0")
            with open(path, "rb") as f:
                while True:
                    chunk = f.read(1 << 20)
                    if not chunk:
                        break
                    h.update(chunk)
            n_files += 1
            n_bytes += os.path.getsize(path)
    return {"sha256": h.hexdigest(), "files": n_files, "bytes": n_bytes}


def _abstract_like(tree: Any):
    """Template pytree -> abstract (shape/dtype/sharding) restore target."""

    def leaf(x):
        if isinstance(x, jax.Array):
            return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
        return x  # scalars / python leaves restore as saved

    return jax.tree_util.tree_map(leaf, tree)


class CheckpointManager:
    """Thin framework wrapper over ``orbax.checkpoint.CheckpointManager``.

    >>> ckpt = CheckpointManager(dir, keep=3)
    >>> ckpt.save(step, state)            # async; returns immediately
    >>> state = ckpt.restore(template=state)   # newest INTACT step
    >>> ckpt.close()
    """

    def __init__(self, directory: str | os.PathLike, *, keep: int = 3,
                 save_interval_steps: int = 1,
                 verify_integrity: bool = True,
                 retry: "RetryPolicy | None" = None):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.directory = os.fspath(directory)
        self.verify_integrity = verify_integrity
        # the sync (queueing) half of save is cheap and idempotent until
        # it succeeds: transient FS errors deserve a second chance, fast
        self._retry = retry if retry is not None else RetryPolicy(
            max_attempts=3, base_delay_s=0.02, max_delay_s=0.5,
            retryable=(OSError, RuntimeError),
        )
        #: steps whose async save has been queued but whose digest is not
        #: yet recorded (digests hash what is ON DISK, so they finalize
        #: at the next wait()/restore()/close() barrier)
        self._pending_digest: "set[int]" = set()
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=keep,
                save_interval_steps=save_interval_steps,
                enable_async_checkpointing=True,
            ),
        )

    # -- save ----------------------------------------------------------------
    def save(self, step: int, state: Any, *, force: bool = False) -> bool:
        """Queue an async save of ``state`` at ``step``.

        Returns False when the manager's save_interval policy skipped it
        (``force=True`` bypasses the policy — used for the final step).
        """
        # span + metrics only for saves that actually happen: the interval
        # policy skips most calls, and ~0s skip spans would pollute the
        # checkpoint.save stage percentiles (monotonic clock: record_span
        # and Request timestamps share time.monotonic)
        t0 = time.monotonic()

        def queue_save():
            fault_point("checkpoint.save")
            return self._mgr.save(
                int(step), args=self._ocp.args.StandardSave(state),
                force=force,
            )

        saved = self._retry.call(queue_save, site="checkpoint.save")
        if saved:
            _M_SAVES.inc()
            _M_SAVE_TIME.observe(time.monotonic() - t0)
            record_span("checkpoint.save", t0, time.monotonic(),
                        step=int(step))
            if self.verify_integrity:
                self._pending_digest.add(int(step))
        return saved

    def wait(self) -> None:
        """Block until every queued async save has landed on disk."""
        t0 = time.perf_counter()
        self._mgr.wait_until_finished()
        self._finalize_digests()
        _M_WAIT_TIME.observe(time.perf_counter() - t0)

    # -- integrity -----------------------------------------------------------
    def _manifest_path(self) -> str:
        return os.path.join(self.directory, MANIFEST_NAME)

    def _load_manifest(self) -> dict:
        try:
            with open(self._manifest_path()) as f:
                return json.load(f)
        except (OSError, ValueError):
            return {}

    def _write_manifest(self, manifest: dict) -> None:
        # same atomicity discipline as the checkpoints themselves
        tmp = self._manifest_path() + ".tmp"
        with open(tmp, "w") as f:
            json.dump(manifest, f, sort_keys=True)
        os.replace(tmp, self._manifest_path())

    def _step_dir(self, step: int) -> "str | None":
        path = os.path.join(self.directory, str(step))
        return path if os.path.isdir(path) else None

    def _finalize_digests(self) -> "dict[int, dict]":
        """Record digests for landed saves and prune GC'd steps. Called
        at the wait()/restore()/close() barriers — the points where the
        async writes are known to be complete on disk.

        Returns the digests computed by THIS call so a restore that just
        finalized a step can verify it without hashing the (possibly
        multi-GB) step dir a second time."""
        fresh: "dict[int, dict]" = {}
        if not self.verify_integrity:
            return fresh
        live = set(self._mgr.all_steps())
        manifest = self._load_manifest()
        changed = False
        for step in sorted(self._pending_digest):
            d = self._step_dir(step)
            if step in live and d is not None:
                digest = checkpoint_digest(d)
                manifest[str(step)] = digest
                fresh[step] = digest
                changed = True
        self._pending_digest.clear()
        stale = [k for k in manifest if int(k) not in live]
        for k in stale:
            del manifest[k]
            changed = True
        if changed:
            self._write_manifest(manifest)
        return fresh

    def _quarantine_step(self, step: int) -> None:
        """Rename a corrupt step dir out of the step namespace.

        The bytes stay on disk for forensics, but the step number is
        freed: a resumed run re-reaching it can save cleanly instead of
        hitting orbax's step-already-exists refusal against the torn
        dir. Best effort — a rename failure only logs (the restore
        fallback already succeeded or is about to raise anyway).
        """
        d = self._step_dir(step)
        if d is None:
            return
        for n in range(100):
            suffix = f"-{n}" if n else ""
            dest = os.path.join(
                self.directory, f"corrupt-step-{int(step)}{suffix}")
            if os.path.exists(dest):
                continue
            try:
                os.rename(d, dest)
            except OSError as e:  # pragma: no cover - fs-dependent
                _log.warning(
                    "could not quarantine corrupt checkpoint step %s "
                    "(%r); a resumed run may fail to re-save it",
                    step, e,
                )
                return
            # orbax caches step metadata in-process: reload so save()
            # stops believing the quarantined step still exists
            self._mgr.reload()
            self._finalize_digests()  # prune the manifest entry
            _log.warning(
                "quarantined corrupt checkpoint step %s -> %s",
                step, dest,
            )
            return

    def verify(self, step: int, *,
               _actual: "dict | None" = None) -> "bool | None":
        """Integrity check of one landed step against the manifest.

        True = digest matches; False = corrupt (mismatch or missing
        files); None = no recorded digest (pre-integrity checkpoint or
        foreign writer) — the caller decides whether to trust it.
        ``_actual`` lets restore() pass the digest its own finalize
        barrier just computed instead of re-hashing the step dir.
        """
        recorded = self._load_manifest().get(str(int(step)))
        if recorded is None:
            return None
        if _actual is None:
            d = self._step_dir(int(step))
            if d is None:
                return False
            _actual = checkpoint_digest(d)
        return _actual["sha256"] == recorded["sha256"]

    # -- restore -------------------------------------------------------------
    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def all_steps(self) -> list[int]:
        return sorted(self._mgr.all_steps())

    def restore(self, step: int | None = None, *, template: Any) -> Any:
        """Restore ``step`` (default: newest INTACT) shaped/sharded like
        ``template``.

        Each ``jax.Array`` leaf of the template contributes its sharding, so
        the restored state lands distributed across the same mesh it was
        initialized for — no host-memory spike, no manual device_put.

        A latest-step restore verifies the candidate against the
        integrity manifest and falls back to the newest step that IS
        intact when the newest write was torn — one corrupt file no
        longer poisons every future resume. Each corrupt step is also
        *quarantined* (its dir renamed out of the step namespace): a
        resumed run will re-reach that step number and re-save it, which
        orbax refuses while the torn dir squats on the name. A step with
        no recorded digest that fails to restore is only quarantined
        after an older step restores successfully — until the template
        is proven good, the failure could be the caller's (wrong
        shape/sharding), and renaming intact history would be
        destructive. An
        explicitly requested ``step`` never falls back and is never
        quarantined: corruption there raises
        :class:`CheckpointCorruptError`. ``verify_integrity=False``
        keeps the pre-integrity behavior exactly: one restore of the
        chosen step, any error propagating as itself.
        """
        # saves still in flight must land before they can be verified
        # (and before orbax can read them back)
        if self._pending_digest:
            self._mgr.wait_until_finished()
        fresh = self._finalize_digests()
        if step is not None:
            candidates = [int(step)]
            pinned = True
        else:
            candidates = sorted(self._mgr.all_steps(), reverse=True)
            pinned = False
        if not candidates:
            raise FileNotFoundError(
                f"no checkpoint found under {self.directory}"
            )
        if not self.verify_integrity:
            return self._do_restore(candidates[0], template)
        errors: "list[str]" = []
        #: steps that failed to restore with NO digest verdict: whether
        #: that is corruption or a bad template only becomes clear when
        #: an older candidate restores (or none does) — see below
        suspects: "list[int]" = []
        #: True once any candidate showed a DIGEST mismatch — the only
        #: evidence strong enough to publish a "corrupt" health verdict
        #: when no candidate restores (all-suspects failures may be the
        #: caller's template and must not 503 the host forever)
        definite_corruption = False
        for i, s in enumerate(candidates):
            ok = self.verify(s, _actual=fresh.get(int(s)))
            if ok is False:
                _M_CORRUPT.inc()
                definite_corruption = True
                flight.record_event(
                    "checkpoint.corrupt", step=int(s),
                    directory=self.directory,
                )
                msg = f"step {s}: integrity digest mismatch (torn write?)"
                _log.error("checkpoint %s under %s", msg, self.directory)
                if pinned:
                    # pinned=True in the fact: the damage is confined to
                    # the REQUESTED step; newer intact history may exist,
                    # so /healthz degrades instead of going unhealthy
                    _integrity_verdict("corrupt", step=int(s),
                                       directory=self.directory,
                                       pinned=True)
                    # inline dump (settle_s=0): the raise below is often
                    # process-fatal, and a daemon settle timer would die
                    # with the interpreter before writing the bundle
                    flight.trigger_dump("checkpoint_corrupt",
                                        settle_s=0, step=int(s))
                    raise CheckpointCorruptError(
                        f"requested checkpoint {msg} under {self.directory}"
                    )
                errors.append(msg)
                self._quarantine_step(s)
                continue
            try:
                out = self._do_restore(s, template)
            except Exception as e:
                if ok is True:
                    # the step verified INTACT on disk, so this failure
                    # is not corruption (template shape/sharding
                    # mismatch, transient device error) — falling back
                    # would silently resume from the wrong step
                    raise
                # unreadable with no digest verdict (pre-manifest
                # checkpoint, or corruption below the digest's radar —
                # including a deleted file's FileNotFoundError): same
                # fallback path. Quarantine is DEFERRED until an older
                # candidate restores: if the failure was really a bad
                # template (wrong shape/sharding), every candidate fails
                # identically, and renaming them all would destroy an
                # intact pre-manifest history over one caller mistake.
                _log.error(
                    "checkpoint step %s under %s failed to restore: %r",
                    s, self.directory, e,
                )
                if pinned:
                    raise
                errors.append(f"step {s}: restore failed: {e!r}")
                suspects.append(int(s))
                continue
            # this restore proves the template matches the on-disk
            # lineage — the newer no-verdict failures really were
            # unreadable, so counting and quarantining them is safe now
            for sus in suspects:
                _M_CORRUPT.inc()
                flight.record_event(
                    "checkpoint.corrupt", step=int(sus),
                    directory=self.directory,
                )
                self._quarantine_step(sus)
            if i > 0:
                _M_FALLBACKS.inc()
                flight.record_event(
                    "checkpoint.fallback", step=int(s),
                    skipped=i, directory=self.directory,
                )
                _integrity_verdict("fallback", step=int(s),
                                   directory=self.directory)
                _log.warning(
                    "restored fallback step %s under %s (newer "
                    "candidate(s) corrupt: %s)",
                    s, self.directory, "; ".join(errors),
                )
            else:
                _integrity_verdict("intact", step=int(s),
                                   directory=self.directory)
            return out
        # only digest-verified damage may 503 the host; every-candidate
        # restore failures without a mismatch could be the caller's
        # template (wrong shape/sharding) and merely degrade health
        _integrity_verdict(
            "corrupt" if definite_corruption else "unreadable",
            directory=self.directory)
        # inline (settle_s=0): the raise below may end the process
        flight.trigger_dump("checkpoint_corrupt", settle_s=0)
        raise CheckpointCorruptError(
            f"no intact checkpoint under {self.directory}: "
            + "; ".join(errors)
        )

    def _do_restore(self, step: int, template: Any) -> Any:
        t0 = time.perf_counter()
        with span("checkpoint.restore", step=int(step)):
            out = self._mgr.restore(
                int(step),
                args=self._ocp.args.StandardRestore(_abstract_like(template)),
            )
        _M_RESTORES.inc()
        _M_RESTORE_TIME.observe(time.perf_counter() - t0)
        return out

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._finalize_digests()
        self._mgr.close()

    def __enter__(self) -> "CheckpointManager":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# -- module-level conveniences (single-shot paths) ---------------------------

def save_and_wait(directory: str | os.PathLike, step: int, state: Any) -> None:
    """Synchronous one-shot save (estimator/model export paths)."""
    with CheckpointManager(directory) as mgr:
        mgr.save(step, state)


def latest_step(directory: str | os.PathLike) -> int | None:
    import orbax.checkpoint as ocp

    if not os.path.isdir(directory):
        return None
    with ocp.CheckpointManager(os.fspath(directory)) as mgr:
        return mgr.latest_step()


def restore_matching(directory: str | os.PathLike, template: Any,
                     step: int | None = None) -> Any:
    """One-shot restore shaped/sharded like ``template``."""
    with CheckpointManager(directory) as mgr:
        return mgr.restore(step, template=template)
