"""Framework-managed checkpoint/resume.

The reference has no checkpoint subsystem: its docs tell users to hang a
Keras ``ModelCheckpoint``/``hvd.callbacks`` off the training loop and write
to DBFS from rank 0 (SURVEY.md §5 "Checkpoint / resume" — user-level only).
Here checkpointing is first-class, the TPU-native way: async sharded Orbax
saves of the full train state (params / opt_state / step), coordinated
across hosts, restored back into the same mesh/shardings for resume after a
barrier-stage retry (SURVEY.md §5 "Failure detection": barrier is
all-or-nothing, restart resumes from checkpoint).
"""

from sparkdl_tpu.checkpoint.manager import (
    CheckpointCorruptError,
    CheckpointManager,
    checkpoint_digest,
    latest_step,
    restore_matching,
    save_and_wait,
)

__all__ = [
    "CheckpointCorruptError",
    "CheckpointManager",
    "checkpoint_digest",
    "latest_step",
    "restore_matching",
    "save_and_wait",
]
