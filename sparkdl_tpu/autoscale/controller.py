"""Fleet-level elastic autoscaler (ISSUE 15, ROADMAP item 4).

PR 8's AutoTuner closed the loop from the metrics spine to the *ingest*
knobs; this controller closes the same loop at the *fleet* level — the
dynamic-placement posture the TensorFlow system paper (arXiv 1605.08695)
argues a long-running service needs. It reads three pressure signals —

* **SLO burn rate** — the worst dimension across every registered
  :class:`~sparkdl_tpu.observability.slo.SLOTracker` (latency burn,
  availability burn);
* **queue depth** — ``sparkdl_queue_depth``, normalized per healthy
  replica;
* **KV deferral streaks** — the block pool's
  :attr:`~sparkdl_tpu.serving.kv_blocks.KVBlockPool.deferral_streak`
  (admissions deferring = capacity pressure *before* it becomes SLO
  burn)

— and actuates three tiers:

* **replicas** — :meth:`ReplicaPool.add_replica` /
  :meth:`ReplicaPool.remove_replica`: scale-down is drain-safe (the
  victim's unstarted work transfers to survivors through the same
  requeue path a quarantine uses — zero accepted requests lost);
* **KV blocks** — :meth:`KVBlockPool.grow` / :meth:`KVBlockPool.shrink`
  between serving and spare capacity: grow on deferral streaks, shrink
  only when the free list covers the worst recorded need;
* **fabric hosts** — :meth:`Router.remove_host`, which rides the PR 14
  ``drain_host`` transfer path, so the router and pool tiers share ONE
  drain contract. Removed handles park on :attr:`AutoScaler.spare_hosts`
  (the caller owns their lifecycle).

The control law is the AutoTuner's discipline transplanted: a direction
must hold for ``hysteresis`` consecutive ticks before anything moves,
every move is one bounded step followed by ``cooldown_ticks`` of
quiet, and every scale-DOWN arms an SLO-burn **veto** — burn at or above
``veto_burn`` inside ``veto_window_ticks`` reverts the move (a replica
comes back, parked KV blocks return to service, a parked fabric host
rejoins) and puts the direction on a ``tabu_ticks`` blocklist. The
fabric tier scales BOTH ways (ISSUE 16, closing the recorded PR 15
gap): a sustained up-vote with no replica headroom re-opens the most
recently parked ``spare_hosts`` handle (``InProcessHost.reopen`` →
``Router.add_host``), bounded by ``max_hosts``; the same rejoin path
reverts a vetoed host scale-down.

Reliability: ``autoscale.decide`` is a fault site at the top of every
decision pass, and the actuators carry their own sites
(``replica.scale_down``, ``kv_pool.resize``) *before* any state moves —
an injected fault therefore **defers** the decision (state
``deferred``, retried next tick) instead of losing work mid-drain.
``/healthz`` reads the controller's state through its flight context
provider: ``degraded`` during a vetoed/deferred scale event, ``ok``
after recovery. Every decision lands in the flight recorder
(``autoscale.decision`` / ``autoscale.veto`` / ``autoscale.deferred``)
and the ``sparkdl_autoscale_*`` metric families.

Pinning: ``replicas=`` or ``SPARKDL_TPU_REPLICAS`` (via the shared
``resolve_pin`` contract) pins the replica count — the controller then
*converges* the pool to the pinned count through the same drain-safe
actuators and never reacts to signals (KV and fabric tiers keep
scaling; they have their own capacity meaning).

Determinism for tests: the signal reader and clock are injectable and
``tick()`` may be driven manually instead of via :meth:`AutoScaler.start`.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Any, Callable, NamedTuple

from sparkdl_tpu.observability import flight
from sparkdl_tpu.observability.registry import GaugeShare, registry
from sparkdl_tpu.reliability.faults import fault_point

__all__ = [
    "AutoScaler",
    "AutoscalePolicy",
    "read_autoscale_signals",
]

_log = logging.getLogger(__name__)

_METRICS = None


class _ScalerMetrics(NamedTuple):
    ticks: Any
    decisions: Any
    vetoes: Any
    deferred: Any
    replicas: Any
    errors: Any


def _metrics() -> _ScalerMetrics:
    global _METRICS
    if _METRICS is None:
        _METRICS = _ScalerMetrics(
            ticks=registry().counter(
                "sparkdl_autoscale_ticks_total",
                "autoscaler control-loop samples taken"),
            decisions=registry().counter(
                "sparkdl_autoscale_decisions_total",
                "autoscaler scale moves applied (reverts included)",
                labels=("actuator", "direction")),
            vetoes=registry().counter(
                "sparkdl_autoscale_vetoes_total",
                "scale-downs reverted/tabued by an SLO-burn spike "
                "inside the veto window",
                labels=("actuator",)),
            deferred=registry().counter(
                "sparkdl_autoscale_deferred_total",
                "scale decisions deferred by a fault mid-pass (the "
                "faulted actuator moved nothing; already-applied "
                "moves this tick keep their cooldown; retried next "
                "tick)"),
            replicas=registry().gauge(
                "sparkdl_autoscale_replicas",
                "replica count of each autoscaled pool, all "
                "controllers"),
            errors=registry().counter(
                "sparkdl_autoscale_tick_errors_total",
                "autoscaler samples that raised outside the decision "
                "path (broken signal reader)"),
        )
    return _METRICS


def read_autoscale_signals() -> "tuple[float, float]":
    """The default signal reader: ``(queue_depth, slo_burn_rate)``
    straight off the spine — the summed ``sparkdl_queue_depth`` gauge
    and the worst burn dimension across every registered SLO tracker
    (sampling them refreshes the ``sparkdl_slo_*`` gauges too, exactly
    like a ``/slo.json`` scrape)."""
    from sparkdl_tpu.observability.slo import slo_report

    burn = 0.0
    for rep in slo_report():
        for dim in ("latency", "availability"):
            d = rep.get(dim)
            if isinstance(d, dict) and d.get("burn_rate") is not None:
                burn = max(burn, float(d["burn_rate"]))
    depth = 0.0
    fam = registry().get("sparkdl_queue_depth")
    if fam is not None:
        for v in fam.snapshot_values().values():
            if isinstance(v, (int, float)):
                depth += float(v)
    return depth, burn


@dataclasses.dataclass(frozen=True)
class AutoscalePolicy:
    """The control-law constants (see module docstring).

    ``queue_high``/``queue_low`` are queued requests PER HEALTHY
    REPLICA: a vote to grow needs sustained depth or burn
    (``burn_high``), a vote to shrink needs BOTH depth and burn quiet
    (``queue_low`` and ``burn_low``) — scale-down is the dangerous
    direction, so its gate is conjunctive. ``veto_burn`` is the
    post-scale-down burn that reverts the move inside
    ``veto_window_ticks``. ``kv_step_blocks`` is the KV resize grain;
    shrink additionally keeps ``2 x kv_step_blocks`` of free headroom
    over the pool's worst recorded need.
    """

    min_replicas: int = 1
    max_replicas: int = 8
    queue_high: float = 4.0
    queue_low: float = 0.5
    burn_high: float = 1.0
    burn_low: float = 0.25
    hysteresis: int = 2
    cooldown_ticks: int = 2
    veto_window_ticks: int = 3
    veto_burn: float = 1.0
    tabu_ticks: int = 20
    kv_step_blocks: int = 8
    min_hosts: int = 1
    max_hosts: int = 8

    def __post_init__(self):
        if self.min_replicas < 1:
            raise ValueError(
                f"min_replicas must be >= 1, got {self.min_replicas}")
        if self.max_replicas < self.min_replicas:
            raise ValueError(
                f"max_replicas {self.max_replicas} < min_replicas "
                f"{self.min_replicas}")
        if self.hysteresis < 1:
            raise ValueError(
                f"hysteresis must be >= 1, got {self.hysteresis}")
        if self.queue_low >= self.queue_high:
            raise ValueError(
                f"queue_low {self.queue_low} must be < queue_high "
                f"{self.queue_high}")
        if self.kv_step_blocks < 1:
            raise ValueError(
                f"kv_step_blocks must be >= 1, got {self.kv_step_blocks}")
        if self.min_hosts < 1:
            raise ValueError(
                f"min_hosts must be >= 1, got {self.min_hosts}")
        if self.max_hosts < self.min_hosts:
            raise ValueError(
                f"max_hosts {self.max_hosts} < min_hosts "
                f"{self.min_hosts}")


class AutoScaler:
    """The fleet controller (see module docstring). Wire any subset of
    actuators::

        scaler = AutoScaler(
            pool=replica_pool,                  # replica tier
            kv_pool=pool, kv_lock=lock,         # engine.kv_autoscale_binding()
            router=router,                      # fabric tier
            policy=AutoscalePolicy(max_replicas=4),
        ).start()

    ``kv_lock`` is the lock guarding the pool's bookkeeping (the engine
    lock — :meth:`ContinuousGPTEngine.kv_autoscale_binding` returns the
    pair). ``signals``/``clock`` are injectable; drive :meth:`tick`
    manually for deterministic tests. ``warmup_arrays`` (optional) is
    dispatched to every replica the controller adds BEFORE it joins
    routing, so scale-up never serves a cold compile to live traffic.
    """

    def __init__(self, *,
                 pool: Any = None,
                 kv_pool: Any = None,
                 kv_lock: "threading.Lock | None" = None,
                 router: Any = None,
                 policy: "AutoscalePolicy | None" = None,
                 replicas: "int | None" = None,
                 warmup_arrays: "dict | None" = None,
                 host_selector: "Callable[[dict], str | None] | None" = None,
                 signals: "Callable[[], tuple] | None" = None,
                 interval_s: float = 0.25,
                 clock: Callable[[], float] = time.monotonic):
        from sparkdl_tpu.ingest.pipeline import resolve_pin

        if pool is None and kv_pool is None and router is None:
            raise ValueError(
                "an AutoScaler needs at least one actuator: pool=, "
                "kv_pool=, or router=")
        if kv_pool is not None and kv_lock is None:
            # a silently-manufactured private lock would let grow/shrink
            # race the engine's allocate/release — the exact corruption
            # kv_autoscale_binding() exists to prevent. Controller-
            # private pools pass their own threading.Lock().
            raise ValueError(
                "kv_pool= needs kv_lock= — the lock that guards the "
                "pool's bookkeeping (ContinuousGPTEngine."
                "kv_autoscale_binding() returns the pair)")
        self.policy = policy if policy is not None else AutoscalePolicy()
        self.pool = pool
        self.kv_pool = kv_pool
        self._kv_lock = kv_lock if kv_lock is not None else threading.Lock()
        self.router = router
        self.warmup_arrays = warmup_arrays
        self._host_selector = host_selector
        pin_value, pinned, pin_source = resolve_pin(
            replicas, "SPARKDL_TPU_REPLICAS", 0, what="replicas")
        #: pinned replica count (None = elastic): the controller
        #: CONVERGES the pool to the pin and never reacts to signals
        self._pin: "int | None" = pin_value if pinned else None
        self._pin_source = pin_source
        self._signals = (signals if signals is not None
                         else read_autoscale_signals)
        self._clock = clock
        self.interval_s = interval_s
        #: "ok" | "deferred" (a decision hit a fault; retrying) |
        #: "vetoed" (a scale-down was reverted; cooling down) — what
        #: healthz_report() reads as degraded until recovery
        self.state = "ok"
        self._streak_dir = 0
        self._streak = 0
        self._cooldown = 0
        #: consecutive quiet ticks where a KV shrink was blocked by
        #: the host tier's unpark reservations (ROADMAP item 1) —
        #: snapshot()/healthz read >0 as degraded; self-clearing
        self._kv_shrink_blocked_streak = 0
        #: direction ("up"/"down") -> ticks it stays blocked
        self._tabu: "dict[str, int]" = {}
        #: armed scale-downs awaiting their SLO-burn verdict
        self._pending_vetoes: "list[dict]" = []
        #: fabric handles removed by fleet scale-down (caller-owned)
        self.spare_hosts: "list[Any]" = []
        self.decision_count = 0
        self.last_decision: "dict | None" = None
        self.last_signals: "dict[str, float]" = {}
        self._g_replicas = GaugeShare(_metrics().replicas)
        self._thread: "threading.Thread | None" = None
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._closed = False
        # process-wide registrations LAST (the engine-constructor rule):
        # /healthz and postmortem bundles read live controller state here
        self._flight_name = flight.add_context_provider(
            f"autoscale-{id(self):x}", self.snapshot)
        flight.record_event(
            "autoscale.start", controller=self._flight_name,
            replicas=(len(pool.replicas) if pool is not None else None),
            pinned=self._pin,
        )
        self._publish_gauges()

    # -- the control loop ----------------------------------------------------
    def tick(self) -> int:
        """One sample -> at most a handful of bounded moves; returns
        the moves applied (reverts included). A fault anywhere in the
        decision path (the ``autoscale.decide`` site, or an actuator's
        own site firing before state moved) DEFERS the decision: state
        ``deferred``, the faulted actuator moved nothing (its site
        fires before mutation), and the pass retries next tick — a
        move that already landed earlier in the same pass keeps its
        post-move cooldown."""
        m = _metrics()
        m.ticks.inc()
        now = self._clock()
        sig = self._signals()
        queue_depth, burn = float(sig[0]), float(sig[1])
        self.last_signals = {"queue_depth": queue_depth,
                             "burn_rate": burn}
        for d in list(self._tabu):
            self._tabu[d] -= 1
            if self._tabu[d] <= 0:
                del self._tabu[d]
        try:
            moved = self._decide(now, queue_depth, burn)
        except Exception as e:
            self.state = "deferred"
            m.deferred.inc()
            flight.record_event(
                "autoscale.deferred", error=type(e).__name__)
            _log.warning("autoscale decision deferred: %r", e)
            moved = 0
        self._publish_gauges()
        return moved

    def _decide(self, now: float, queue_depth: float,
                burn: float) -> int:
        fault_point("autoscale.decide")
        if self.state == "deferred":
            self.state = "ok"  # the decision path is reachable again
        moved = 0
        # 1) the veto watch runs FIRST — including during cooldown: a
        # scale-down that spikes burn must revert promptly
        if self._pending_vetoes:
            if burn >= self.policy.veto_burn:
                # the veto IS this tick's decision: the reverts land,
                # cooldown starts NEXT tick, and the vetoed state holds
                # until that cooldown recovers
                return self._veto_all(burn)
            else:
                for entry in self._pending_vetoes:
                    entry["ticks"] -= 1
                self._pending_vetoes = [
                    e for e in self._pending_vetoes if e["ticks"] > 0]
        # 2) post-move cooldown: the last move's effect is what the
        # next vote must see, not the transient it caused
        if self._cooldown > 0:
            self._cooldown -= 1
            self._streak = 0
            self._streak_dir = 0
            if self._cooldown == 0 and self.state == "vetoed" \
                    and not self._pending_vetoes:
                self.state = "ok"  # recovered
            return moved
        if self.state == "vetoed" and not self._pending_vetoes:
            self.state = "ok"
        # 3) pinned replica count: converge, never react
        if self._pin is not None:
            moved += self._converge_pin()
            if moved:
                self._cooldown = self.policy.cooldown_ticks
            return moved
        # 4) urgent KV grow first: a deferral streak is LIVE pressure
        # (admissions deferring right now), no hysteresis needed
        moved += self._kv_grow_if_starved()
        try:
            # 5) replica/fleet tier: direction vote with hysteresis
            direction = self._vote(queue_depth, burn)
            from sparkdl_tpu.serving import tenancy

            if direction < 0 and tenancy.overload_level() > 0:
                # brownout veto (ISSUE 20): the process is above normal
                # on the overload ladder — shrinking capacity now would
                # deepen the very overload the ladder is shedding. A
                # down-vote simply does not count until level 0.
                direction = 0
                flight.record_event(
                    "autoscale.overload_vetoed_down",
                    level=tenancy.overload_level())
            key = "up" if direction > 0 else "down"
            if direction == 0 or key in self._tabu:
                self._streak = 0
                self._streak_dir = 0
            else:
                if direction != self._streak_dir:
                    self._streak_dir = direction
                    self._streak = 1
                else:
                    self._streak += 1
                if self._streak >= self.policy.hysteresis:
                    moved += (self._scale_up() if direction > 0
                              else self._scale_down())
                    self._streak = 0
                    self._streak_dir = 0
            # 6) KV shrink LAST, and only on a tick where nothing else
            # moved and the queue is quiet too: parking capacity mid-
            # spike (or mid-scale) would starve the very scale-up the
            # spike needs — each shrink's cooldown would eat the
            # up-vote's window
            if not moved:
                moved += self._kv_shrink_if_quiet(queue_depth, burn)
        except Exception:
            # a later actuator faulted (the pass defers) — but a KV
            # grow that already landed this tick keeps its post-move
            # cooldown: the one-bounded-move discipline holds even on
            # a deferred pass
            if moved:
                self._cooldown = self.policy.cooldown_ticks
            raise
        if moved:
            self._cooldown = self.policy.cooldown_ticks
        return moved

    def _vote(self, queue_depth: float, burn: float) -> int:
        per = queue_depth / max(1, self._healthy_replicas())
        if per >= self.policy.queue_high or burn >= self.policy.burn_high:
            return 1
        if per <= self.policy.queue_low and burn <= self.policy.burn_low:
            return -1
        return 0

    def _healthy_replicas(self) -> int:
        if self.pool is not None:
            return sum(1 for r in list(self.pool.replicas)
                       if not r.quarantined)
        if self.router is not None:
            return int(self.router.snapshot().get("healthy_count") or 1)
        return 1

    # -- actuators -----------------------------------------------------------
    def _scale_up(self) -> int:
        if self.pool is not None \
                and len(self.pool.replicas) < self.policy.max_replicas:
            index = self.pool.add_replica(
                warmup_arrays=self.warmup_arrays)
            self._record("replica", "up", replica=index,
                         replicas=len(self.pool.replicas))
            return 1
        if (self.router is not None and self.spare_hosts
                and len(self.router.hosts()) < self.policy.max_hosts):
            # fabric-tier scale-UP (ISSUE 16): re-open the most
            # recently parked handle and rejoin it — the scaler can
            # grow a tier again, not just shrink it
            host = self._rejoin_spare_host()
            if host is not None:
                self._record("host", "up", host=host,
                             hosts=len(self.router.hosts()))
                return 1
        return 0

    def _rejoin_spare_host(self) -> "str | None":
        """Reopen the newest ``spare_hosts`` handle and rejoin it via
        :meth:`Router.add_host`. On failure the handle goes back on the
        spare list (nothing is half-joined: add_host is the last step)."""
        handle = self.spare_hosts.pop()
        try:
            fn = getattr(handle, "reopen", None)
            if callable(fn):
                fn()
            return self.router.add_host(handle)
        except Exception:
            self.spare_hosts.append(handle)
            _log.warning(
                "spare-host rejoin failed (handle stays parked)",
                exc_info=True)
            return None

    def _scale_down(self) -> int:
        pool = self.pool
        if pool is not None \
                and len(pool.replicas) > self.policy.min_replicas:
            # short join: the transfer + in-flight-completion contract
            # does not depend on the worker's exit, and a wedged victim
            # stays under the pool's watchdog scan — the control loop
            # (veto watch, urgent KV grow) must not stall 30 s on it
            index = pool.remove_replica(timeout_s=1.0)
            self._record("replica", "down", replica=index,
                         replicas=len(pool.replicas))
            self._arm_veto("replica", {})
            return 1
        if self.router is not None \
                and len(self.router.hosts()) > self.policy.min_hosts:
            host = self._select_host()
            if host is not None:
                # rides drain_host: unstarted requests transfer to
                # survivors; the handle parks as spare capacity
                handle = self.router.remove_host(host)
                self.spare_hosts.append(handle)
                self._record("host", "down", host=host,
                             hosts=len(self.router.hosts()))
                self._arm_veto("host", {"host": host})
                return 1
        return 0

    def _select_host(self) -> "str | None":
        snap = self.router.snapshot()
        hosts = [h for h in snap.get("hosts", ())
                 if not h.get("draining")]
        if self._host_selector is not None:
            return self._host_selector(snap)
        if not hosts:
            return None
        # least outstanding work = cheapest drain
        return min(hosts, key=lambda h: h.get("outstanding") or 0)["host"]

    def _kv_grow_if_starved(self) -> int:
        pool = self.kv_pool
        if pool is None:
            return 0
        with self._kv_lock:
            starved = pool.deferral_streak > 0 and pool.spare_count > 0
            if not starved:
                return 0
            n = pool.grow(self.policy.kv_step_blocks)
            # the kv_pool.resize site fires inside grow() BEFORE any
            # bookkeeping moves: an injected fault propagates out of
            # this tick as a deferred decision
        if n:
            self._record("kv", "up", blocks=n, spare=pool.spare_count)
            return 1
        return 0

    def _kv_shrink_if_quiet(self, queue_depth: float,
                            burn: float) -> int:
        pool = self.kv_pool
        if pool is None:
            return 0
        step = self.policy.kv_step_blocks
        per = queue_depth / max(1, self._healthy_replicas())
        with self._kv_lock:
            quiet = (pool.deferral_streak == 0
                     and burn <= self.policy.burn_low
                     and per <= self.policy.queue_low
                     and pool.free_count >= max(1, pool.need_peak)
                     + 2 * step)
            if not quiet:
                return 0
            n = pool.shrink(step)
            reserved = getattr(pool, "unpark_reserved", 0)
        if n:
            self._kv_shrink_blocked_streak = 0
            self._record("kv", "down", blocks=n,
                         spare=pool.spare_count)
            self._arm_veto("kv", {"blocks": n})
            return 1
        if reserved > 0:
            # quiet by every signal, yet shrink moved nothing: the
            # host tier's unpark reservations hold the floor (ROADMAP
            # item 1). Defer — scaling down now would strand parked
            # sessions' resumes behind re-prefills. The streak reads
            # as degraded in healthz and self-clears when sessions
            # resume (reservations drop) or the next shrink lands.
            self._kv_shrink_blocked_streak += 1
            if self._kv_shrink_blocked_streak == 1:
                # record the episode's start, not every blocked tick —
                # the streak in snapshot()/healthz carries the duration
                self._record("kv", "shrink_blocked",
                             unpark_reserved=reserved)
        else:
            self._kv_shrink_blocked_streak = 0
        return 0

    def _converge_pin(self) -> int:
        if self.pool is None:
            return 0
        cur = len(self.pool.replicas)
        target = max(1, int(self._pin or 0))
        if cur < target:
            index = self.pool.add_replica(
                warmup_arrays=self.warmup_arrays)
            self._record("replica", "up", replica=index, pinned=True)
            return 1
        if cur > target:
            index = self.pool.remove_replica(timeout_s=1.0)
            self._record("replica", "down", replica=index, pinned=True)
            return 1
        return 0

    # -- veto ----------------------------------------------------------------
    def _arm_veto(self, actuator: str, detail: dict) -> None:
        self._pending_vetoes.append({
            "actuator": actuator,
            "ticks": self.policy.veto_window_ticks,
            "detail": detail,
        })

    def _veto_all(self, burn: float) -> int:
        """SLO burn spiked inside a scale-down's veto window: revert
        every armed scale-down (replica back in, parked KV blocks back
        in service, the parked host handle reopened and rejoined),
        tabu the direction, and read degraded until the cooldown
        recovers."""
        vetoes, self._pending_vetoes = self._pending_vetoes, []
        n = 0
        for entry in vetoes:
            actuator = entry["actuator"]
            _metrics().vetoes.inc(actuator=actuator)
            reverted = False
            if actuator == "replica" and self.pool is not None \
                    and len(self.pool.replicas) < self.policy.max_replicas:
                # the ceiling binds reverts too: a scale-up that landed
                # between the scale-down and this veto must not let the
                # revert push the pool past max_replicas
                try:
                    self.pool.add_replica(
                        warmup_arrays=self.warmup_arrays)
                    reverted = True
                except Exception:
                    _log.warning("veto revert add_replica failed "
                                 "(tabu still holds)", exc_info=True)
            elif actuator == "kv" and self.kv_pool is not None:
                with self._kv_lock:
                    blocks = int(entry["detail"].get("blocks") or 0)
                    try:
                        reverted = self.kv_pool.grow(blocks) > 0
                    except Exception:
                        _log.warning("veto revert kv grow failed "
                                     "(tabu still holds)",
                                     exc_info=True)
            elif actuator == "host" and self.router is not None \
                    and self.spare_hosts \
                    and len(self.router.hosts()) < self.policy.max_hosts:
                # the rejoin path (ISSUE 16) closes the PR 15 tabu-only
                # asymmetry: a vetoed host scale-down brings the parked
                # handle back instead of waiting for an operator
                reverted = self._rejoin_spare_host() is not None
            self._record(actuator, "revert", reverted=reverted,
                         burn=round(burn, 3))
            flight.record_event(
                "autoscale.veto", actuator=actuator,
                burn=round(burn, 3), reverted=reverted)
            n += 1
        self._tabu["down"] = self.policy.tabu_ticks
        self._cooldown = max(self._cooldown, self.policy.cooldown_ticks)
        self.state = "vetoed"
        return n

    # -- bookkeeping ---------------------------------------------------------
    def _record(self, actuator: str, direction: str, **detail) -> None:
        _metrics().decisions.inc(actuator=actuator, direction=direction)
        self.decision_count += 1
        self.last_decision = {"actuator": actuator,
                              "direction": direction, **detail}
        # the decision HISTORY is what postmortems need (the AutoTuner
        # lesson): the replica count alone hides the causality
        flight.record_event(
            "autoscale.decision", actuator=actuator,
            direction=direction, **detail)

    def _publish_gauges(self) -> None:
        if self.pool is not None:
            self._g_replicas.set(
                0 if self._closed else len(self.pool.replicas))

    def snapshot(self) -> "dict[str, Any]":
        """Operator/healthz view, under the ``"autoscaler"`` key the
        :func:`~sparkdl_tpu.observability.flight.healthz_report`
        aggregation reads (``vetoed``/``deferred`` -> degraded)."""
        kv = None
        if self.kv_pool is not None:
            kv = {
                "serving": self.kv_pool.serving_count,
                "spare": self.kv_pool.spare_count,
                "free": self.kv_pool.free_count,
                "need_peak": self.kv_pool.need_peak,
                "deferral_streak": self.kv_pool.deferral_streak,
                "unpark_reserved": getattr(
                    self.kv_pool, "unpark_reserved", 0),
                "shrink_blocked_streak": self._kv_shrink_blocked_streak,
            }
        return {"autoscaler": {
            "state": self.state,
            "replicas": (len(self.pool.replicas)
                         if self.pool is not None else None),
            "pinned": self._pin,
            "pin_source": self._pin_source,
            "cooldown_ticks": self._cooldown,
            "tabu": dict(self._tabu),
            "pending_vetoes": len(self._pending_vetoes),
            "decisions": self.decision_count,
            "last_decision": self.last_decision,
            "signals": dict(self.last_signals),
            "kv": kv,
            "hosts": (len(self.router.hosts())
                      if self.router is not None else None),
            "spare_hosts": len(self.spare_hosts),
        }}

    # -- cadence thread / lifecycle ------------------------------------------
    def start(self) -> "AutoScaler":
        """Run :meth:`tick` every ``interval_s`` on a daemon thread
        (idempotent; the AutoTuner's fresh-stop-event discipline)."""
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return self
            stop = self._stop = threading.Event()
            self._thread = threading.Thread(
                target=self._loop, args=(stop,),
                name="sparkdl-autoscale", daemon=True,
            )
            self._thread.start()
        return self

    def _loop(self, stop: threading.Event) -> None:
        logged = False
        while not stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:
                # tick() already absorbs decision-path faults as
                # "deferred"; only a broken signal reader lands here —
                # count every failure, log the first with traceback
                _metrics().errors.inc()
                if not logged:
                    logged = True
                    _log.warning(
                        "autoscaler tick failed (continuing; counted "
                        "in sparkdl_autoscale_tick_errors_total)",
                        exc_info=True)
                continue

    def stop(self) -> None:
        with self._lock:
            self._stop.set()
            t = self._thread
            self._thread = None
        if t is not None and t.is_alive():
            t.join(timeout=2.0)

    def close(self) -> None:
        """Stop the cadence thread and retract process-wide
        registrations (idempotent). Actuated objects are NOT closed —
        the caller owns pool/engine/router lifecycles."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self.stop()
        flight.record_event(
            "autoscale.close", controller=self._flight_name,
            decisions=self.decision_count)
        flight.remove_context_provider(self._flight_name)
        self._g_replicas.set(0)

    def __enter__(self) -> "AutoScaler":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()
