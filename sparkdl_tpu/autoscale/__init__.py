"""Elastic fleet autoscaling: SLO-driven grow/shrink with drain-safe
scale-down (ISSUE 15, ROADMAP item 4).

:class:`AutoScaler` closes the loop from the observability spine (SLO
burn, queue depth, KV deferral streaks) to the fleet's capacity knobs:
replica count (:meth:`ReplicaPool.add_replica` /
:meth:`ReplicaPool.remove_replica`), the KV block pool's serving/spare
split (:meth:`KVBlockPool.grow` / :meth:`KVBlockPool.shrink`), and
fabric host membership (:meth:`Router.remove_host` over the shared
drain path). The control law is PR 8's AutoTuner discipline —
hysteresis, post-move cooldown, and an SLO-burn veto that reverts a
scale-down and tabus the direction. See
:mod:`sparkdl_tpu.autoscale.controller`.
"""

from sparkdl_tpu.autoscale.controller import (
    AutoScaler,
    AutoscalePolicy,
    read_autoscale_signals,
)

__all__ = [
    "AutoScaler",
    "AutoscalePolicy",
    "read_autoscale_signals",
]
