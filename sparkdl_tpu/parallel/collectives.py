"""Collective helpers used inside shard_map'd train steps.

Horovod's C++ engine (SURVEY.md 2.16) exists to fuse gradient tensors and
drive a NCCL ring; under XLA the fusion and scheduling belong to the
compiler, so the framework-level deliverable is just the right collective
in the right place. These helpers are the vocabulary the train steps use.

All functions take pytrees and an axis name (or tuple of names) and are
meant to be called *inside* ``jax.shard_map`` / under a mesh context —
outside one, jax raises an unbound-axis error, which is the correct
failure mode.
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
import jax.numpy as jnp
from jax import lax

AxisNames = str | Sequence[str]


def cross_replica_mean(tree: Any, axis: AxisNames = "dp") -> Any:
    """Mean-allreduce a pytree over the data axes.

    The Horovod-parity op: ``hvd.DistributedOptimizer`` averages gradients
    across the ring; here it is one ``psum`` divided by the axis size,
    compiled onto ICI.
    """
    n = lax.psum(1, axis)
    return jax.tree.map(lambda g: lax.psum(g, axis) / n, tree)


def psum_grads(grads: Any, axis: AxisNames = "dp") -> Any:
    """Sum-allreduce gradients (caller owns any scaling)."""
    return jax.tree.map(lambda g: lax.psum(g, axis), grads)


def reduce_scatter_grads(grads: Any, axis: str = "fsdp") -> Any:
    """Reduce-scatter gradients over ``axis`` along each leaf's dim 0.

    The ZeRO/FSDP half of the ring-allreduce: each device keeps only its
    shard of the summed gradient. Leaves whose dim 0 is not divisible by
    the axis size are fully reduced instead (scalars, small biases).
    """
    n = lax.psum(1, axis)

    def _rs(g: jax.Array) -> jax.Array:
        if g.ndim == 0 or g.shape[0] % n != 0:
            return lax.psum(g, axis)
        return lax.psum_scatter(g, axis, scatter_dimension=0, tiled=True)

    return jax.tree.map(_rs, grads)


def all_gather_params(params: Any, axis: str = "fsdp", *, full_shapes: Any = None) -> Any:
    """All-gather FSDP-sharded params along dim 0 for use in the forward.

    ``full_shapes`` — a matching pytree of the *unsharded* leaf shapes
    (e.g. from ``jax.eval_shape`` of the init) — tells us which leaves
    :func:`reduce_scatter_grads` actually scattered: those whose dim 0 was
    divisible by the axis size. Leaves it left whole (scalars, small
    biases) are returned as-is instead of being gathered into n stacked
    copies. Without ``full_shapes``, every ndim>0 leaf is assumed sharded.
    """
    n = lax.psum(1, axis)

    def _ag(p: jax.Array, full=None) -> jax.Array:
        if p.ndim == 0:
            return p
        if full is not None and (len(full.shape) == 0 or full.shape[0] % n != 0):
            return p  # was never scattered
        return lax.all_gather(p, axis, axis=0, tiled=True)

    if full_shapes is None:
        return jax.tree.map(_ag, params)
    return jax.tree.map(_ag, params, full_shapes)


def global_norm(tree: Any, axis: AxisNames | None = None) -> jax.Array:
    """L2 norm of a pytree; if ``axis`` given, the *global* norm of a tree
    whose leaves are sharded over that axis (sums squares with psum)."""
    sq = sum(jnp.sum(jnp.square(x.astype(jnp.float32))) for x in jax.tree.leaves(tree))
    if axis is not None:
        sq = lax.psum(sq, axis)
    return jnp.sqrt(sq)
