"""Parallelism strategies over the named device mesh.

The reference's only parallelism is data parallelism (SURVEY.md 2.11): one
TF session per Spark executor for inference, a Horovod NCCL ring for
training (2.13/2.16/2.17). On TPU those collectives are not a user-space
library but XLA programs over ICI — this package owns the idiomatic forms:

- :mod:`collectives` — shard_map-level collective helpers (grad psum,
  reduce-scatter/all-gather param sync) replacing Horovod's fused
  ring-allreduce engine.
- :mod:`ring_attention` — sequence/context parallelism: blockwise attention
  with K/V blocks rotating around the ``sp`` ring via ``ppermute``
  (long-context support the reference never had).
- :mod:`tensor_parallel` — column/row-parallel Dense + TP attention/MLP
  layers with the ``psum`` placed exactly once per block.
- :mod:`pipeline` — collective-permute pipeline parallelism over the ``pp``
  axis (GPipe schedule via ``lax.scan``).
- :mod:`expert_parallel` — GShard/Switch-style MoE over the ``ep`` axis
  (token-choice routing, capacity masks, GSPMD all-to-all dispatch).

Axis names are the canonical ones from ``sparkdl_tpu.runtime.mesh``.
"""

from sparkdl_tpu.parallel.collectives import (
    all_gather_params,
    cross_replica_mean,
    psum_grads,
    reduce_scatter_grads,
)
from sparkdl_tpu.parallel.ring_attention import ring_attention, ring_self_attention
from sparkdl_tpu.parallel.tensor_parallel import (
    ColumnParallelDense,
    RowParallelDense,
    TPMlpBlock,
)
from sparkdl_tpu.parallel.pipeline import pipeline_apply
from sparkdl_tpu.parallel.expert_parallel import (
    MoEMlpBlock,
    moe_aux_losses,
    top_k_dispatch,
)

__all__ = [
    "all_gather_params",
    "cross_replica_mean",
    "psum_grads",
    "reduce_scatter_grads",
    "ring_attention",
    "ring_self_attention",
    "ColumnParallelDense",
    "RowParallelDense",
    "TPMlpBlock",
    "pipeline_apply",
    "MoEMlpBlock",
    "moe_aux_losses",
    "top_k_dispatch",
]
