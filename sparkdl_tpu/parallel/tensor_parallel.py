"""Tensor parallelism: Megatron-style column/row-parallel layers, TPU form.

On GPU+NCCL this is hand-written all-reduce calls between matmul halves; on
TPU the idiomatic form is GSPMD: the layers below carry *sharding metadata*
on their kernels (``nn.with_partitioning``) and sharding constraints on
activations, and XLA inserts the ICI collectives during partitioning. The
pairing is the classic one:

- :class:`ColumnParallelDense` — kernel split on the **output** dim
  (``tp``); activations come out tp-sharded, no communication.
- :class:`RowParallelDense` — kernel split on the **input** dim; the
  partial products are summed by an all-reduce XLA places at the output.

``ColumnParallelDense -> gelu -> RowParallelDense`` therefore costs exactly
one psum per MLP block, the Megatron recipe, without a single explicit
collective in the model code.

Use :func:`init_sharded` to initialise a module's params already placed
according to their metadata over a mesh (eval_shape + jit, so the full
params never materialise on one device).
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sparkdl_tpu.runtime.mesh import mesh_context

Dtype = Any


def _active_mesh():
    """The mesh in scope, across jax versions: ``get_abstract_mesh`` when
    the runtime has it (jax >= 0.5), else the thread-local physical mesh
    (0.4.x spells the same 'which mesh am I under' question that way)."""
    try:
        return jax.sharding.get_abstract_mesh()
    except AttributeError:  # jax < 0.5
        from jax._src import mesh as mesh_lib

        return mesh_lib.thread_resources.env.physical_mesh


def constrain_dim(x: jax.Array, axis: str, dim: int = -1) -> jax.Array:
    """Constrain one dim of ``x`` to ``axis``; the others stay UNCONSTRAINED
    so GSPMD keeps whatever batch/sequence sharding is in flight. ``dim=-1``
    is the tp feature-dim form; expert_parallel uses ``dim=0`` for the
    leading expert dim. No-op outside a mesh context (single-device tests)
    or under shard_map over the axis (arrays are already per-device blocks);
    a mesh without the axis is a real error and propagates."""
    mesh = _active_mesh()
    if mesh.empty:
        return x
    if axis not in mesh.axis_names:
        raise ValueError(
            f"axis {axis!r} not in the active mesh axes {mesh.axis_names}"
        )
    if axis in getattr(mesh, "manual_axes", ()):
        return x
    parts: list = [P.UNCONSTRAINED] * x.ndim
    parts[dim] = axis
    return lax.with_sharding_constraint(x, P(*parts))


class ColumnParallelDense(nn.Module):
    """Dense with kernel sharded [in, out/tp]; output stays tp-sharded."""

    features: int
    tp_axis: str = "tp"
    use_bias: bool = True
    dtype: Dtype = jnp.float32
    kernel_init: Callable = nn.initializers.lecun_normal()

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        kernel = self.param(
            "kernel",
            nn.with_partitioning(self.kernel_init, (None, self.tp_axis)),
            (x.shape[-1], self.features),
            self.dtype,
        )
        y = jnp.dot(x.astype(self.dtype), kernel)
        if self.use_bias:
            bias = self.param(
                "bias",
                nn.with_partitioning(nn.initializers.zeros_init(), (self.tp_axis,)),
                (self.features,),
                self.dtype,
            )
            y = y + bias
        return constrain_dim(y, self.tp_axis)


class RowParallelDense(nn.Module):
    """Dense with kernel sharded [in/tp, out]; XLA all-reduces the output."""

    features: int
    tp_axis: str = "tp"
    use_bias: bool = True
    dtype: Dtype = jnp.float32
    kernel_init: Callable = nn.initializers.lecun_normal()

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        kernel = self.param(
            "kernel",
            nn.with_partitioning(self.kernel_init, (self.tp_axis, None)),
            (x.shape[-1], self.features),
            self.dtype,
        )
        y = jnp.dot(x.astype(self.dtype), kernel)
        if self.use_bias:
            # Bias is replicated; added once, after the implicit reduce.
            bias = self.param(
                "bias",
                nn.with_partitioning(nn.initializers.zeros_init(), (None,)),
                (self.features,),
                self.dtype,
            )
            y = y + bias
        return y


class TPMlpBlock(nn.Module):
    """Column-parallel up-projection -> activation -> row-parallel down.

    One ICI all-reduce per block (the Megatron MLP shape)."""

    hidden_features: int
    out_features: int
    tp_axis: str = "tp"
    activation: Callable = nn.gelu
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        h = ColumnParallelDense(
            self.hidden_features, tp_axis=self.tp_axis, dtype=self.dtype,
            name="up",
        )(x)
        h = self.activation(h)
        return RowParallelDense(
            self.out_features, tp_axis=self.tp_axis, dtype=self.dtype,
            name="down",
        )(h)


def param_shardings(params: Any, mesh: Mesh) -> Any:
    """Pytree of NamedShardings from the boxed partitioning metadata."""
    def _one(leaf):
        if isinstance(leaf, nn.Partitioned):
            return NamedSharding(mesh, leaf.get_partition_spec())
        return NamedSharding(mesh, P())

    return jax.tree.map(
        _one, params, is_leaf=lambda x: isinstance(x, nn.Partitioned)
    )


def init_sharded(
    module: nn.Module,
    rng: jax.Array,
    sample_inputs: Sequence[jax.Array],
    mesh: Mesh,
) -> Any:
    """Initialise params directly into their annotated shardings.

    eval_shape first, then a jitted init with out_shardings — so no device
    ever holds the unsharded model (how a >HBM model must be initialised).
    Returns the *unboxed* param pytree, placed on the mesh.
    """
    abstract = jax.eval_shape(module.init, rng, *sample_inputs)
    shardings = param_shardings(abstract, mesh)

    def _init(r):
        variables = module.init(r, *sample_inputs)
        return nn.meta.unbox(variables)

    with mesh_context(mesh):
        return jax.jit(_init, out_shardings=shardings)(rng)
