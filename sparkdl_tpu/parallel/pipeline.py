"""Pipeline parallelism over the ``pp`` mesh axis.

GPipe-style schedule in SPMD form: every ``pp`` peer holds one stage's
params; activations hop stage-to-stage via ``ppermute`` while microbatches
stream in, so after the pp-1-step fill the pipe computes all stages
concurrently. The whole schedule is one ``lax.scan`` — no Python-level
round trips, fully differentiable, and XLA overlaps the neighbour permute
with the stage compute.

The reference has nothing like this (SURVEY.md 2.11: no PP anywhere); it
exists here because a framework claiming model-scale training on TPU pods
needs stages that exceed one chip's HBM.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from sparkdl_tpu.compat import shard_map

def stack_stage_params(per_stage_params: list[Any]) -> Any:
    """Stack per-stage param pytrees along a new leading (pp) dim."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage_params)


def _pipeline_local(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    params: Any,
    x_mb: jax.Array,
    *,
    axis_name: str,
) -> jax.Array:
    """Per-device schedule. x_mb: [num_mb, mb, ...] replicated on all peers;
    params: this stage's pytree (leading pp dim already squeezed)."""
    pp = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    is_first = my_idx == 0
    is_last = my_idx == pp - 1
    num_mb = x_mb.shape[0]
    total_steps = num_mb + pp - 1
    perm = [(i, (i + 1) % pp) for i in range(pp)]

    # Output microbatch shape = stage_fn output shape (probe without FLOPs).
    # Contract: every stage maps activations to the SAME shape/dtype, so the
    # inter-stage buffer and the injected input share it.
    out_shape = jax.eval_shape(stage_fn, params, x_mb[0])
    out_buf = jnp.zeros((num_mb,) + out_shape.shape, out_shape.dtype)

    def step(carry, t):
        recv, out_buf = carry
        # Stage 0 injects microbatch t (zeros once the pipe is draining);
        # later stages consume what the previous stage sent last step.
        feed_idx = jnp.clip(t, 0, num_mb - 1)
        my_in = jnp.where(is_first, x_mb[feed_idx], recv)
        y = stage_fn(params, my_in)
        # Last stage commits finished microbatch t-(pp-1).
        out_idx = jnp.clip(t - (pp - 1), 0, num_mb - 1)
        valid = is_last & (t >= pp - 1) & (t - (pp - 1) < num_mb)
        committed = jnp.where(valid, y, out_buf[out_idx])
        out_buf = out_buf.at[out_idx].set(committed)
        # Hand activations to the next stage (the last->first wrap lands on
        # stage 0, which ignores it — it always injects fresh input).
        recv = lax.ppermute(y, axis_name, perm)
        return (recv, out_buf), None

    # The loop body makes the carries device-varying (ppermute / axis_index
    # selects); mark the initial values as such for the VMA type system.
    # Older jax has no VMA typing (lax.pcast) and needs no declaration.
    recv0 = jnp.zeros(out_shape.shape, out_shape.dtype)
    if hasattr(lax, "pcast"):
        recv0 = lax.pcast(recv0, (axis_name,), to="varying")
        out_buf = lax.pcast(out_buf, (axis_name,), to="varying")
    (_, out_buf), _ = lax.scan(step, (recv0, out_buf), jnp.arange(total_steps))
    # Only the last stage holds real outputs; broadcast over the ring.
    out_buf = jnp.where(is_last, out_buf, jnp.zeros_like(out_buf))
    return lax.psum(out_buf, axis_name)


def pipeline_apply(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stacked_params: Any,
    x: jax.Array,
    mesh: Mesh,
    *,
    num_microbatches: int,
    axis_name: str = "pp",
) -> jax.Array:
    """Run ``x`` through ``pp`` chained stages of ``stage_fn``.

    ``stacked_params``: per-stage pytrees stacked on dim 0 (length = pp axis
    size, see :func:`stack_stage_params`); each stage must map activations
    to activations of the same shape (the usual transformer-block contract).
    ``x``: [B, ...] with B divisible by ``num_microbatches``.
    """
    if x.shape[0] % num_microbatches:
        raise ValueError(
            f"batch {x.shape[0]} not divisible by microbatches {num_microbatches}"
        )
    x_mb = x.reshape((num_microbatches, x.shape[0] // num_microbatches) + x.shape[1:])

    def local(params, x_mb):
        params = jax.tree.map(lambda p: jnp.squeeze(p, 0), params)
        return _pipeline_local(stage_fn, params, x_mb, axis_name=axis_name)

    out_mb = shard_map(
        local,
        mesh=mesh,
        in_specs=(P(axis_name), P()),
        out_specs=P(),
    )(stacked_params, x_mb)
    return out_mb.reshape((x.shape[0],) + out_mb.shape[2:])
