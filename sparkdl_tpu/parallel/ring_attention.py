"""Ring attention: exact attention over sequences sharded on the ``sp`` axis.

Long-context support the reference never had (SURVEY.md §5 "Long-context:
entirely absent") but that is first-class here: each ``sp`` peer holds one
sequence block of Q/K/V; K/V blocks rotate around the ring via ``ppermute``
while every device folds each visiting block into a numerically-stable
online softmax (flash-attention style running max/denominator). Peak memory
per device is O(L/sp · L/sp) for the score block; communication is sp-1
neighbour hops riding ICI, overlapped by XLA with the block matmuls.

The math is the blockwise-parallel form of

    softmax(Q K^T / sqrt(d)) V

computed as sp partial reductions — results are exact (up to fp) vs. full
attention, which is what the oracle test asserts.
"""

from __future__ import annotations

import functools
from typing import Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from sparkdl_tpu.compat import shard_map
_NEG_INF = -1e30  # large-negative instead of -inf: keeps exp()/where() NaN-free


def _block_attend(q, k, v, o, m, l, *, q_offset, k_offset, causal, scale,
                  kv_mask=None):
    """Fold one visiting K/V block into the running (o, m, l) accumulators.

    q: [B, Lq, H, D]   k, v: [B, Lk, H, D]
    o: [B, Lq, H, D] f32 accumulator (un-normalised)
    m: [B, H, Lq] f32 running max,  l: [B, H, Lq] f32 running denominator
    kv_mask: optional [B, Lk] bool — False keys are masked out (padding).
    """
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        lq, lk = q.shape[1], k.shape[1]
        q_pos = q_offset + jnp.arange(lq)
        k_pos = k_offset + jnp.arange(lk)
        mask = q_pos[:, None] >= k_pos[None, :]
        s = jnp.where(mask[None, None], s, _NEG_INF)
    if kv_mask is not None:
        s = jnp.where(kv_mask[:, None, None, :], s, _NEG_INF)

    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    correction = jnp.exp(m - m_new)  # [B, H, Lq]
    p = jnp.exp(s - m_new[..., None])  # [B, H, Lq, Lk]
    l_new = l * correction + jnp.sum(p, axis=-1)
    pv = jnp.einsum(
        "bhqk,bkhd->bqhd", p, v.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    o_new = o * correction.transpose(0, 2, 1)[..., None] + pv
    return o_new, m_new, l_new


def _ring_attention_local(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    kv_mask: jax.Array | None = None,
    *,
    axis_name: str,
    causal: bool,
    scale: float | None,
) -> jax.Array:
    """Per-device body; call inside shard_map with q/k/v local blocks.

    kv_mask: optional [B, Lk_local] bool padding mask for this device's
    keys; it rides the ring alongside its K/V block.
    """
    orig_dtype = q.dtype
    b, lq, h, d = q.shape
    lk = k.shape[1]
    if scale is None:
        scale = 1.0 / (d ** 0.5)

    axis_size = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    o0 = jnp.zeros((b, lq, h, d), jnp.float32)
    m0 = jnp.full((b, h, lq), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, lq), jnp.float32)
    # Accumulators become device-varying inside the loop (they mix in q/k/v,
    # which vary over the mesh axes of the enclosing shard_map); the scan
    # carry type must declare that up front. Older jax has no
    # varying-manual-axes typing (jax.typeof/.vma/pcast) — there the carry
    # needs no declaration, so skip.
    if hasattr(jax, "typeof"):
        vma = tuple(jax.typeof(q).vma)
        if vma:
            o0, m0, l0 = (
                lax.pcast(t, vma, to="varying") for t in (o0, m0, l0)
            )
    masked = kv_mask is not None

    def step(carry, i):
        o, m, l, k_blk, v_blk, mask_blk = carry
        kv_idx = (my_idx - i) % axis_size  # whose block we hold at hop i
        o, m, l = _block_attend(
            q, k_blk, v_blk, o, m, l,
            q_offset=my_idx * lq, k_offset=kv_idx * lk,
            causal=causal, scale=scale,
            kv_mask=mask_blk if masked else None,
        )
        # Rotate K/V (and the padding mask, when present) to the next peer
        # (skipping the hop after the final fold would be ideal; one extra
        # hop keeps the scan body uniform and XLA overlaps it with the
        # epilogue anyway).
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        if masked:
            mask_blk = lax.ppermute(mask_blk, axis_name, perm)
        return (o, m, l, k_blk, v_blk, mask_blk), None

    carry0 = (o0, m0, l0, k, v, kv_mask if masked else jnp.zeros((), bool))
    (o, m, l, *_), _ = lax.scan(step, carry0, jnp.arange(axis_size))
    denom = jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]  # [B,Lq,H,1]
    return (o / denom).astype(orig_dtype)


def ring_self_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    kv_mask: jax.Array | None = None,
    *,
    axis_name: str = "sp",
    causal: bool = False,
    scale: float | None = None,
) -> jax.Array:
    """Ring attention on already-local [B, L/sp, H, D] blocks.

    Use this form inside a model that is itself under shard_map/pjit with
    sequence dim sharded on ``axis_name``. ``kv_mask``: [B, L/sp] bool
    padding mask for this device's keys.
    """
    return _ring_attention_local(
        q, k, v, kv_mask, axis_name=axis_name, causal=causal, scale=scale
    )


def allgather_self_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    kv_mask: jax.Array | None = None,
    *,
    axis_name: str = "sp",
    causal: bool = False,
) -> jax.Array:
    """All-gather attention on already-local [B, L/sp, H, D] blocks.

    The small-``sp`` alternative to the ring schedule: gather every
    peer's K/V once (one tiled all-gather riding ICI) and run the dense
    masked softmax for the LOCAL query shard over the FULL key sequence
    — scale by division, mask with the global causal offsets, softmax
    over the whole row at once. Because each query row's math is then
    EXACTLY the single-device full-attention computation (no online
    max/denominator re-association), the result is **bitwise-identical**
    to unsharded attention — the property the serving prefill's parity
    contract rides. Memory is O(L) gathered keys per chip (vs the
    ring's O(L/sp)), which is why the ring stays the long-context /
    large-``sp`` schedule.
    """
    import math

    b, lq, h, d = q.shape
    kg = lax.all_gather(k, axis_name, axis=1, tiled=True)  # [B, L, H, D]
    vg = lax.all_gather(v, axis_name, axis=1, tiled=True)
    my_idx = lax.axis_index(axis_name)
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, kg, preferred_element_type=jnp.float32,
    ) / math.sqrt(d)
    if causal:
        q_pos = my_idx * lq + jnp.arange(lq)
        k_pos = jnp.arange(kg.shape[1])
        mask = q_pos[:, None] >= k_pos[None, :]
        s = jnp.where(mask[None, None], s, _NEG_INF)
    if kv_mask is not None:
        mg = lax.all_gather(kv_mask, axis_name, axis=1, tiled=True)
        s = jnp.where(mg[:, None, None, :], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", p, vg)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    kv_mask: jax.Array | None = None,
    axis_name: str = "sp",
    causal: bool = False,
    scale: float | None = None,
    batch_axes: Sequence[str] = ("dp", "fsdp"),
) -> jax.Array:
    """Ring attention on global [B, L, H, D] arrays over ``mesh``.

    Shards the sequence dim over ``axis_name`` (and batch over
    ``batch_axes``), runs the ring, returns the global [B, L, H, D] result.
    ``kv_mask``: optional [B, L] bool — False key positions (padding) are
    excluded from attention.
    """
    spec = P(tuple(batch_axes), axis_name, None, None)
    mask_spec = P(tuple(batch_axes), axis_name)
    fn = functools.partial(
        _ring_attention_local, axis_name=axis_name, causal=causal, scale=scale
    )
    if kv_mask is None:
        return shard_map(
            fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec
        )(q, k, v)
    return shard_map(
        fn, mesh=mesh, in_specs=(spec, spec, spec, mask_spec), out_specs=spec
    )(q, k, v, kv_mask)
