"""Expert parallelism: Mixture-of-Experts layers sharded over ``ep``.

The reference has no expert parallelism of any kind (SURVEY.md 2.11 — its
only parallelism is DP); this module exists because a TPU-pod framework
needs the fourth classic axis alongside tp/pp/sp. The design is the
GShard/Switch token-choice form, expressed the idiomatic TPU way:

- Expert weights live as single arrays with a leading expert dim,
  annotated ``(ep, ...)`` via ``nn.with_partitioning`` — one expert (or a
  contiguous group of experts) per ``ep`` peer.
- Routing produces dense dispatch/combine tensors (static shapes, capacity
  bounded) and token->expert movement is two einsums. When the token batch
  is dp-sharded and the expert dim ep-sharded, GSPMD lowers those einsums
  to ICI **all-to-alls** — the hand-written `alltoall` of GPU MoE stacks
  is compiler-inserted here, never written by hand.
- Everything is static-shape: top-k selection and capacity overflow are
  masks, not gathers with data-dependent sizes, so the whole layer jits
  and differentiates cleanly (overflowed tokens contribute zero and fall
  through the residual connection).

Aux losses follow Switch Transformer: a load-balancing loss (sowed under
``intermediates/aux_loss``) pushes the router toward uniform expert usage,
and router z-loss (``intermediates/router_z_loss``) keeps logits bounded.
"""

from __future__ import annotations

import math
from typing import Any, Callable

import flax.linen as nn
import jax
import jax.numpy as jnp
from sparkdl_tpu.parallel.tensor_parallel import constrain_dim

Dtype = Any


def _constrain_leading(x: jax.Array, axis: str) -> jax.Array:
    """Constrain dim 0 (the expert dim) to ``axis``; the rest stays
    UNCONSTRAINED (shared contract: tensor_parallel.constrain_dim)."""
    return constrain_dim(x, axis, dim=0)


def top_k_dispatch(
    gates: jax.Array, k: int, capacity: int
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Token-choice top-k assignment with per-expert capacity.

    gates: [G, S, E] router probabilities (softmax output, f32).
    Returns (combine, dispatch, aux_loss):
      combine  [G, S, E, C] f32 — gate weight of token s in expert e's
               capacity slot c (zero if unrouted/overflowed),
      dispatch [G, S, E, C] bool — combine > 0,
      aux_loss scalar f32 — Switch load-balancing loss (1.0 = perfectly
               balanced, grows as routing collapses onto few experts).

    Tokens pick experts greedily (slot 0 = argmax, slot 1 = second
    choice, ...); positions within an expert's capacity go in token order
    (cumsum), tokens past capacity are dropped for that slot. All shapes
    static; everything differentiable w.r.t. ``gates`` through ``combine``.
    """
    g, s, e = gates.shape
    if not 1 <= k <= e:
        raise ValueError(f"k={k} must be in [1, num_experts={e}]")
    combine = jnp.zeros((g, s, e, capacity), jnp.float32)
    counts = jnp.zeros((g, e), jnp.float32)  # tokens routed per expert so far
    masked = gates
    first_choice = None
    for _ in range(k):
        idx = jnp.argmax(masked, axis=-1)  # [G, S]
        onehot = jax.nn.one_hot(idx, e, dtype=jnp.float32)  # [G, S, E]
        if first_choice is None:
            first_choice = onehot
        # Position of each token inside its chosen expert's buffer.
        pos = jnp.cumsum(onehot, axis=1) - onehot + counts[:, None, :]
        pos_tok = jnp.sum(pos * onehot, axis=-1)  # [G, S]
        within = (pos_tok < capacity).astype(jnp.float32)
        # Gate weight from the *masked* gates: identical to the original
        # value for a live pick, but exactly zero when a token's remaining
        # gates have all underflowed to 0 (argmax of an all-zero row says
        # expert 0; reading the unmasked gate would double-count it).
        gate_val = jnp.sum(masked * onehot, axis=-1)  # [G, S]
        cap_onehot = jax.nn.one_hot(
            pos_tok.astype(jnp.int32), capacity, dtype=jnp.float32
        )  # [G, S, C]
        combine = combine + (
            (gate_val * within)[:, :, None, None]
            * onehot[:, :, :, None]
            * cap_onehot[:, :, None, :]
        )
        counts = counts + jnp.sum(onehot, axis=1)
        masked = masked * (1.0 - onehot)  # exclude chosen expert next slot

    dispatch = combine > 0.0
    # Switch aux loss: E * <fraction routed to e (slot 0)> . <mean gate of e>
    density = jnp.mean(first_choice, axis=1)  # [G, E]
    density_proxy = jnp.mean(gates, axis=1)  # [G, E]
    aux_loss = jnp.mean(density * density_proxy) * (e**2)
    return combine, dispatch, aux_loss


class MoEMlpBlock(nn.Module):
    """Mixture-of-experts MLP: router -> top-k dispatch -> per-expert
    up/act/down -> weighted combine.

    Drop-in for a dense MLP block on [..., S, M] activations (2-D [N, M]
    input is treated as one group). Expert weights are stacked on a leading
    expert dim annotated with ``ep_axis`` — initialise with
    ``tensor_parallel.init_sharded`` to place them. Inside a dp x ep mesh
    the dispatch/combine einsums become ICI all-to-alls (see module doc).

    ``capacity_factor`` bounds per-expert work: capacity =
    ceil(S * k / E * capacity_factor) (>= 1 row per expert). Overflowed
    tokens get zero output for that slot — pair with a residual connection.
    """

    num_experts: int
    hidden_features: int
    k: int = 2
    capacity_factor: float = 1.25
    ep_axis: str = "ep"
    activation: Callable = nn.gelu
    dtype: Dtype = jnp.float32
    kernel_init: Callable = nn.initializers.lecun_normal()

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        squeeze = x.ndim == 2
        if squeeze:
            x = x[None]  # [1, N, M]
        lead = x.shape[:-2]
        g = math.prod(lead) if lead else 1
        s, m = x.shape[-2], x.shape[-1]
        tokens = x.reshape(g, s, m)

        # Router in f32 (logit stability), replicated weights.
        logits = nn.Dense(
            self.num_experts,
            use_bias=False,
            dtype=jnp.float32,
            param_dtype=jnp.float32,
            kernel_init=self.kernel_init,
            name="router",
        )(tokens.astype(jnp.float32))
        gates = jax.nn.softmax(logits, axis=-1)

        capacity = max(
            1, math.ceil(s * self.k / self.num_experts * self.capacity_factor)
        )
        combine, dispatch, aux = top_k_dispatch(gates, self.k, capacity)
        self.sow("intermediates", "aux_loss", aux)
        self.sow(
            "intermediates",
            "router_z_loss",
            jnp.mean(jax.nn.logsumexp(logits, axis=-1) ** 2),
        )

        wi = self.param(
            "wi",
            nn.with_partitioning(self.kernel_init, (self.ep_axis, None, None)),
            (self.num_experts, m, self.hidden_features),
            self.dtype,
        )
        wo = self.param(
            "wo",
            nn.with_partitioning(self.kernel_init, (self.ep_axis, None, None)),
            (self.num_experts, self.hidden_features, m),
            self.dtype,
        )

        # dispatch: tokens -> [E, G, C, M] expert buffers (all-to-all under
        # GSPMD when tokens are dp-sharded and E is ep-sharded).
        expert_in = jnp.einsum(
            "gsec,gsm->egcm", dispatch.astype(self.dtype), tokens.astype(self.dtype)
        )
        expert_in = _constrain_leading(expert_in, self.ep_axis)
        h = self.activation(jnp.einsum("egcm,emh->egch", expert_in, wi))
        expert_out = jnp.einsum("egch,ehm->egcm", h, wo)
        expert_out = _constrain_leading(expert_out, self.ep_axis)
        # combine: expert buffers -> tokens, weighted by the gate values.
        y = jnp.einsum(
            "gsec,egcm->gsm", combine.astype(self.dtype), expert_out
        )

        y = y.reshape(x.shape)
        return y[0] if squeeze else y


def moe_aux_losses(intermediates: Any) -> dict[str, jax.Array]:
    """Sum every sowed MoE aux/z loss in an ``intermediates`` collection.

    Use: ``(y, inters) = model.apply(vars, x, mutable=['intermediates'])``
    then add ``alpha * losses['aux_loss'] + beta * losses['router_z_loss']``
    to the task loss.
    """
    out = {"aux_loss": jnp.zeros(()), "router_z_loss": jnp.zeros(())}
    flat = jax.tree_util.tree_flatten_with_path(intermediates)[0]
    for path, leaf in flat:
        names = [getattr(p, "key", getattr(p, "name", "")) for p in path]
        for key in out:
            if key in names:
                out[key] = out[key] + jnp.sum(leaf)
    return out
