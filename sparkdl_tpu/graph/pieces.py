"""Reusable graph pieces: the Spark-image-struct → float-tensor converter.

Parity with the reference (SURVEY.md 2.10, [U: python/sparkdl/graph/
pieces.py] buildSpImageConverter): a graph fragment that turns the raw image
struct fields (height, width, nChannels, data bytes) into a float image
tensor inside the model graph, handling BGR→RGB. Two forms are provided:

- :func:`buildSpImageConverter` — a TF ``GraphFunction`` piece, for splicing
  into ingested TF graphs (UDF composition, TFImageTransformer).
- :func:`image_batch_to_float` — the JAX-native equivalent used on the hot
  path, where decode already happened host-side and the batch is a dense
  uint8/float32 NHWC array.
"""

from __future__ import annotations

import jax.numpy as jnp


def buildSpImageConverter(channelOrder: str = "BGR", img_dtype: str = "uint8"):
    """Build the struct→tensor converter as a TF GraphFunction.

    Inputs (placeholders): ``height`` (int32), ``width`` (int32),
    ``image_buffer`` (raw bytes, string scalar), ``nChannels`` (int32).
    Output: ``sp_image`` float32 tensor of shape (height, width, nChannels)
    in **RGB** channel order (flipped when the struct stores BGR).
    """
    tf = _tf()
    from sparkdl_tpu.graph.builder import IsolatedSession

    if img_dtype not in ("uint8", "float32"):
        raise ValueError(f"unsupported image dtype {img_dtype!r}")
    if channelOrder not in ("BGR", "RGB", "L"):
        raise ValueError(f"unsupported channelOrder {channelOrder!r}")

    with IsolatedSession() as issn:
        height = tf.compat.v1.placeholder(tf.int32, [], name="height")
        width = tf.compat.v1.placeholder(tf.int32, [], name="width")
        num_channels = tf.compat.v1.placeholder(tf.int32, [], name="nChannels")
        image_buffer = tf.compat.v1.placeholder(tf.string, [], name="image_buffer")

        decode_dtype = tf.uint8 if img_dtype == "uint8" else tf.float32
        flat = tf.io.decode_raw(image_buffer, decode_dtype)
        shape = tf.stack([height, width, num_channels])
        image = tf.reshape(flat, shape)
        image = tf.cast(image, tf.float32)
        if channelOrder == "BGR":
            image = tf.reverse(image, axis=[-1])
        image = tf.identity(image, name="sp_image")
        return issn.asGraphFunction(
            [height, width, num_channels, image_buffer], [image],
            strip_and_freeze=False,
        )


def image_batch_to_float(batch, channel_order: str = "BGR"):
    """JAX-native converter: dense NHWC batch → float32 RGB batch.

    The hot-path twin of :func:`buildSpImageConverter`: by the time data is
    on device it is already a dense array (host decode via imageIO), so the
    remaining conversion — dtype cast and BGR→RGB — happens on the TPU where
    XLA fuses it into the first model op.
    """
    x = jnp.asarray(batch).astype(jnp.float32)
    if channel_order == "BGR" and x.shape[-1] >= 3:
        x = jnp.concatenate([x[..., 2::-1], x[..., 3:]], axis=-1)
    return x


def _tf():
    from sparkdl_tpu.graph._tf import require_tf

    return require_tf()
