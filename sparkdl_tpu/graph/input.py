"""TFInputGraph — unified ingestion of TF model artifacts.

Parity with the reference (SURVEY.md 2.7, [U: python/sparkdl/graph/input.py]):
six constructors normalize (live graph | GraphDef | checkpoint | SavedModel,
each optionally signature-driven) into one frozen-graph value with optional
signature→tensor-name maps, consumed by TFTransformer/TFImageTransformer.
The TPU-native difference is the exit path: :meth:`to_jax` lowers the frozen
graph into a jittable JAX function instead of shipping it to a JVM TF session.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Sequence

from sparkdl_tpu.graph import utils as tfx
from sparkdl_tpu.graph._tf import require_tf
from sparkdl_tpu.graph.builder import GraphFunction, IsolatedSession, strip_and_freeze_upto

_SERVING = "serving_default"
_SERVE_TAG = "serve"


@dataclasses.dataclass(eq=False)  # identity semantics: used as a cache key
class TFInputGraph:
    """A frozen TF graph plus endpoint metadata.

    ``input_tensor_name_from_signature`` / ``output_tensor_name_from_signature``
    map signature keys (e.g. ``"images"``) to tensor names (``"x:0"``); they
    are None when the artifact carried no signature.
    """

    graph_def: Any
    input_tensor_name_from_signature: "dict[str, str] | None"
    output_tensor_name_from_signature: "dict[str, str] | None"
    input_names: list[str]
    output_names: list[str]

    # -- signature translation (reference API) ----------------------------
    def translateInputMapping(self, input_mapping) -> dict[str, str]:
        """column→signature-key mapping → column→tensor-name mapping."""
        items = input_mapping.items() if isinstance(input_mapping, dict) else input_mapping
        out = {}
        for col, key in sorted(items):
            out[col] = self._resolve(key, self.input_tensor_name_from_signature)
        return out

    def translateOutputMapping(self, output_mapping) -> dict[str, str]:
        """signature-key→column mapping → tensor-name→column mapping."""
        items = output_mapping.items() if isinstance(output_mapping, dict) else output_mapping
        out = {}
        for key, col in sorted(items):
            out[self._resolve(key, self.output_tensor_name_from_signature)] = col
        return out

    def _resolve(self, key: str, table: "dict[str, str] | None") -> str:
        if table is not None:
            if key in table:
                return table[key]
            raise KeyError(
                f"signature key {key!r} not found; available: {sorted(table)}"
            )
        return tfx.tensor_name(key)

    # -- TPU-native exit --------------------------------------------------
    def asGraphFunction(self) -> GraphFunction:
        return GraphFunction(self.graph_def, list(self.input_names), list(self.output_names))

    def to_jax(self) -> Callable[..., tuple]:
        """Jittable JAX function over arrays in ``input_names`` order."""
        return self.asGraphFunction().to_jax()

    # -- constructors -----------------------------------------------------
    @classmethod
    def fromGraph(cls, graph, sess, feed_names: Sequence[str], fetch_names: Sequence[str]) -> "TFInputGraph":
        """From a live tf.Graph + session (variables frozen through sess)."""
        return _from_session(graph, sess, feed_names, fetch_names, None)

    @classmethod
    def fromGraphDef(cls, graph_def, feed_names: Sequence[str], fetch_names: Sequence[str]) -> "TFInputGraph":
        """From a serialized (already frozen) GraphDef."""
        tf = require_tf()
        with IsolatedSession() as issn:
            tf.graph_util.import_graph_def(graph_def, name="")
            return _from_session(
                issn.graph, issn.sess, feed_names, fetch_names, None
            )

    @classmethod
    def fromCheckpoint(cls, checkpoint_dir: str, feed_names: Sequence[str], fetch_names: Sequence[str]) -> "TFInputGraph":
        """From a TF-1-style checkpoint directory (MetaGraph + variables)."""
        with _restored_checkpoint(checkpoint_dir) as (issn, _meta):
            return _from_session(issn.graph, issn.sess, feed_names, fetch_names, None)

    @classmethod
    def fromCheckpointWithSignature(cls, checkpoint_dir: str, signature_def_key: str = _SERVING) -> "TFInputGraph":
        """Checkpoint whose MetaGraph carries a signature_def."""
        with _restored_checkpoint(checkpoint_dir) as (issn, meta):
            sig = _signature(meta, signature_def_key)
            return _from_session(issn.graph, issn.sess, None, None, sig)

    @classmethod
    def fromSavedModel(
        cls, saved_model_dir: str, tag_set: str = _SERVE_TAG,
        feed_names: Sequence[str] = (), fetch_names: Sequence[str] = (),
    ) -> "TFInputGraph":
        """From a SavedModel with explicit feed/fetch tensor names.

        TF2 (object-graph) SavedModels freeze through the TF2 loader; the
        feed/fetch names then address the FROZEN graph of the serving
        signature (arg placeholders like ``"x:0"`` plus the inlined body's
        hierarchical op names), since TF2 variables cannot restore into a
        v1 session.
        """
        if _is_tf2_saved_model(saved_model_dir, tag_set):
            return _from_tf2_saved_model(
                saved_model_dir, tag_set, feed_names, fetch_names, None
            )
        with _loaded_saved_model(saved_model_dir, tag_set) as (issn, _meta):
            return _from_session(issn.graph, issn.sess, feed_names, fetch_names, None)

    @classmethod
    def fromSavedModelWithSignature(
        cls, saved_model_dir: str, tag_set: str = _SERVE_TAG,
        signature_def_key: str = _SERVING,
    ) -> "TFInputGraph":
        """From a SavedModel, endpoints resolved through its signature_def.

        Handles both generations: TF1-style SavedModels load into a v1
        session and freeze there; TF2 (object-graph) SavedModels — what
        ``tf.saved_model.save``/Keras export — load through the TF2 loader
        and freeze via ``convert_variables_to_constants_v2``, which also
        inlines the ``tf.function`` call tree, so the result translates
        natively on TPU.
        """
        if _is_tf2_saved_model(saved_model_dir, tag_set):
            return _from_tf2_saved_model(
                saved_model_dir, tag_set, None, None, signature_def_key
            )
        with _loaded_saved_model(saved_model_dir, tag_set) as (issn, meta):
            sig = _signature(meta, signature_def_key)
            return _from_session(issn.graph, issn.sess, None, None, sig)


# -- internals -------------------------------------------------------------

def _is_tf2_saved_model(saved_model_dir: str, tag_set: str) -> bool:
    """True when the tagged MetaGraph carries a TF2 object graph (saved by
    ``tf.saved_model.save`` / Keras export): its variables live in the
    object graph and cannot restore into a v1 session."""
    require_tf()
    from tensorflow.python.saved_model import loader_impl

    try:
        sm = loader_impl.parse_saved_model(saved_model_dir)
    except Exception:
        return False
    tags = {t for t in (tag_set or "").split(",") if t}
    for mg in sm.meta_graphs:
        if tags <= set(mg.meta_info_def.tags):
            return len(mg.object_graph_def.nodes) > 0
    return False


def _from_tf2_saved_model(
    saved_model_dir: str, tag_set: str,
    feed_names, fetch_names, signature_def_key: "str | None",
) -> TFInputGraph:
    """TF2 loader + ``convert_variables_to_constants_v2`` freeze.

    The v2 freeze inlines the traced ``tf.function`` call tree
    (PartitionedCall sites and their library bodies) while folding
    variables, so the stored GraphDef is flat and native-translatable —
    the TPU-honest form of the reference's "run any SavedModel" promise
    (SURVEY.md 2.7).
    """
    tf = require_tf()
    from tensorflow.python.framework.convert_to_constants import (
        convert_variables_to_constants_v2,
    )

    tags = [t for t in (tag_set or "").split(",") if t] or None
    obj = tf.saved_model.load(saved_model_dir, tags=tags)
    sigs = dict(obj.signatures)
    if signature_def_key is not None:
        if signature_def_key not in sigs:
            raise KeyError(
                f"signature_def {signature_def_key!r} not found; "
                f"available: {sorted(sigs)}"
            )
        key = signature_def_key
    elif _SERVING in sigs:
        key = _SERVING
    elif len(sigs) == 1:
        key = next(iter(sigs))
    else:
        raise ValueError(
            "TF2 SavedModel with multiple signatures and no "
            f"signature_def_key; available: {sorted(sigs)}"
        )

    cf = sigs[key]
    frozen = convert_variables_to_constants_v2(cf)
    gdef = frozen.graph.as_graph_def(add_shapes=True)

    # signature key -> frozen tensor name. Inputs: the signature wrapper's
    # arg specs are named by signature key and flatten in the same order
    # as the frozen placeholders. Outputs: structured_outputs of the
    # ORIGINAL signature fn keeps the key->tensor dict; the frozen fn's
    # outputs follow the same (key-sorted) flatten order.
    in_specs = [
        s for s in tf.nest.flatten(cf.structured_input_signature)
        if isinstance(s, tf.TensorSpec)
    ]
    in_map = {
        (spec.name or f"input_{i}"): t.name
        for i, (spec, t) in enumerate(zip(in_specs, frozen.inputs))
    }
    so = cf.structured_outputs
    if isinstance(so, dict):
        out_keys = sorted(so)
    else:
        out_keys = [f"output_{i}" for i in range(len(frozen.outputs))]
    out_map = dict(zip(out_keys, (t.name for t in frozen.outputs)))

    if signature_def_key is None and (feed_names or fetch_names):
        input_names = [
            tfx.validated_input(t, frozen.graph) for t in feed_names
        ]
        output_names = [
            tfx.validated_output(t, frozen.graph) for t in fetch_names
        ]
        return TFInputGraph(gdef, None, None, input_names, output_names)

    input_names = [tfx.tensor_name(v) for v in in_map.values()]
    output_names = [tfx.tensor_name(v) for v in out_map.values()]
    return TFInputGraph(gdef, dict(in_map), dict(out_map),
                        input_names, output_names)


def _signature(meta_graph_def, key: str):
    sigs = meta_graph_def.signature_def
    if key not in sigs:
        raise KeyError(
            f"signature_def {key!r} not found; available: {sorted(sigs)}"
        )
    sig = sigs[key]
    inputs = {k: v.name for k, v in sig.inputs.items()}
    outputs = {k: v.name for k, v in sig.outputs.items()}
    return inputs, outputs


def _from_session(graph, sess, feed_names, fetch_names, sig) -> TFInputGraph:
    if sig is not None:
        in_map, out_map = sig
        input_names = [tfx.validated_input(t, graph) for t in in_map.values()]
        output_names = [tfx.validated_output(t, graph) for t in out_map.values()]
        in_table = {k: tfx.tensor_name(v) for k, v in in_map.items()}
        out_table = {k: tfx.tensor_name(v) for k, v in out_map.items()}
    else:
        input_names = [tfx.validated_input(t, graph) for t in feed_names]
        output_names = [tfx.validated_output(t, graph) for t in fetch_names]
        in_table = out_table = None
    gdef = strip_and_freeze_upto(sess, graph, output_names)
    return TFInputGraph(gdef, in_table, out_table, input_names, output_names)


class _restored_checkpoint:
    """Context manager: IsolatedSession with a checkpoint restored into it."""

    def __init__(self, checkpoint_dir: str):
        self.checkpoint_dir = checkpoint_dir

    def __enter__(self):
        tf = require_tf()
        ckpt = tf.train.latest_checkpoint(self.checkpoint_dir)
        if ckpt is None:
            raise FileNotFoundError(
                f"no checkpoint found under {self.checkpoint_dir!r}"
            )
        from tensorflow.python.framework import meta_graph as _mg

        meta = _mg.read_meta_graph_file(ckpt + ".meta")
        self._issn = IsolatedSession()
        self._issn.__enter__()
        try:
            saver = tf.compat.v1.train.import_meta_graph(meta, clear_devices=True)
            if saver is not None:
                saver.restore(self._issn.sess, ckpt)
        except BaseException:
            self._issn.__exit__(None, None, None)
            raise
        return self._issn, meta

    def __exit__(self, *exc):
        return self._issn.__exit__(*exc)


class _loaded_saved_model:
    """Context manager: IsolatedSession with a SavedModel loaded into it."""

    def __init__(self, saved_model_dir: str, tag_set: str):
        self.saved_model_dir = saved_model_dir
        self.tags = [t for t in (tag_set or "").split(",") if t]

    def __enter__(self):
        tf = require_tf()
        self._issn = IsolatedSession()
        self._issn.__enter__()
        try:
            meta = tf.compat.v1.saved_model.loader.load(
                self._issn.sess, self.tags, self.saved_model_dir
            )
        except BaseException:
            self._issn.__exit__(None, None, None)
            raise
        return self._issn, meta

    def __exit__(self, *exc):
        return self._issn.__exit__(*exc)
