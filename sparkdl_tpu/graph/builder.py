"""Graph surgery: IsolatedSession, GraphFunction, freeze/strip, JAX lowering.

Parity with the reference's builder (SURVEY.md 2.9, [U:
python/sparkdl/graph/builder.py]). ``IsolatedSession`` keeps every ingestion
in a fresh ``tf.Graph`` so no state leaks across models (the reference's
race-isolation discipline); ``GraphFunction`` is the serializable
(graph_def, inputs, outputs) unit. The TPU-native addition is
:meth:`GraphFunction.to_jax`: lower the frozen graph through TF's XLA bridge
(``jax2tf.call_tf``) into a function that jits, fuses and shards like any
other JAX code — the reference instead ships the GraphDef to a JVM-side TF
session ([U: tensorframes], SURVEY.md 2.15).
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any, Callable, Sequence

from sparkdl_tpu.graph import utils as tfx
from sparkdl_tpu.graph._tf import require_tf

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class GraphFunction:
    """A frozen TF computation: GraphDef + ordered input/output tensor names."""

    graph_def: Any  # tf.compat.v1.GraphDef
    input_names: list[str]
    output_names: list[str]

    def dump(self, path: str) -> None:
        """Serialize to a file (proto bytes + name lists, self-contained)."""
        import json

        payload = {
            "input_names": self.input_names,
            "output_names": self.output_names,
        }
        with open(path, "wb") as f:
            header = json.dumps(payload).encode("utf-8")
            f.write(len(header).to_bytes(8, "little"))
            f.write(header)
            f.write(self.graph_def.SerializeToString())

    @staticmethod
    def load(path: str) -> "GraphFunction":
        import json

        tf = require_tf()
        with open(path, "rb") as f:
            n = int.from_bytes(f.read(8), "little")
            payload = json.loads(f.read(n).decode("utf-8"))
            gdef = tf.compat.v1.GraphDef()
            gdef.ParseFromString(f.read())
        return GraphFunction(gdef, payload["input_names"], payload["output_names"])

    # -- TPU-native lowering ----------------------------------------------
    def to_jax(self, validate: bool = True,
               prefer_native: bool = True,
               f32_precision: str = "highest") -> Callable[..., tuple]:
        """Lower to a jittable JAX function ``f(*arrays) -> tuple(arrays)``.

        Inputs follow ``input_names`` order. Two lowering paths:

        1. **Native translation** (graph/tf2jax.py) — the frozen graph is
           rebuilt as JAX ops, so it jits/fuses/shards on TPU with no TF
           in the execution path. Used whenever every op is covered.
        2. **call_tf fallback** — TF's XLA bridge inlines the graph into
           the surrounding program. Requires a TF build with kernels for
           the target platform: works on CPU hosts, but CPU-only TF
           wheels cannot emit TPU programs (no XLA_TPU_JIT kernels), so
           on TPU this path fails at first trace — which is why the
           native translator is tried first.

        The supported-op surface (graph/op_surface.py) is enforced here:
        graphs holding host-side/stateful ops that can never compile raise
        :class:`~sparkdl_tpu.graph.op_surface.UnsupportedGraphOpsError`
        with per-node guidance; ``validate=False`` skips the prescreen, in
        which case ops XLA cannot compile fail at first trace with the XLA
        error.

        ``f32_precision``: "highest" (default, TF-session-faithful f32
        contractions) or "default" (TPU bf16 passes, ~6x faster) — native
        path only.
        """
        if f32_precision not in ("highest", "default"):
            raise ValueError(
                f"f32_precision must be 'highest' or 'default', "
                f"got {f32_precision!r}"
            )
        if validate:
            from sparkdl_tpu.graph.op_surface import validate_graph_def

            validate_graph_def(self.graph_def,
                               output_names=self.output_names)
        gdef = self.graph_def
        in_names = list(self.input_names)
        out_names = list(self.output_names)

        def make_call_tf():
            tf = require_tf()
            from jax.experimental import jax2tf

            specs = placeholder_specs(gdef, in_names)

            def tf_fn(*tensors):
                mapping = dict(zip(in_names, tensors))
                outs = tf.graph_util.import_graph_def(
                    gdef, input_map=mapping, return_elements=out_names,
                    name="",
                )
                return tuple(outs)

            wrapped = tf.compat.v1.wrap_function(tf_fn, signature=specs)
            lowered = jax2tf.call_tf(wrapped, has_side_effects=False)

            def fn(*arrays):
                out = lowered(*arrays)
                return out if isinstance(out, (tuple, list)) else (out,)

            return fn

        if not prefer_native:
            return make_call_tf()

        from sparkdl_tpu.graph.tf2jax import (
            GraphTranslationError,
            translate_graph_def,
        )

        # translate_graph_def inlines TF2 function-call sites itself and
        # raises GraphTranslationError when any op is outside the native
        # surface — one scan, one contract. call_tf (below) keeps the
        # ORIGINAL graph: a TF session executes function calls natively.
        try:
            native_fn = translate_graph_def(
                gdef, in_names, out_names, f32_precision=f32_precision
            )
        except GraphTranslationError:
            return make_call_tf()

        # Op names are all covered, but an ATTR combination may still be
        # outside the translation surface (NCHW convs, align-corners
        # resizes, ...), which only surfaces when the translator walks the
        # graph with real inputs. Fall back to call_tf at that point, once,
        # so such graphs keep working wherever TF can compile them. The
        # caught set is wider than GraphTranslationError because translator
        # internals can surface unsupported patterns as TypeError/
        # ValueError/IndexError (shape math, numpy conversion); errors
        # raised by the fallback itself propagate.
        chosen: list = []

        def fn(*arrays):
            if chosen:
                return chosen[0](*arrays)
            try:
                out = native_fn(*arrays)
                chosen.append(native_fn)
                return out
            except (GraphTranslationError, TypeError, ValueError,
                    IndexError, NotImplementedError):
                # latch the fallback only once it has actually produced a
                # result — a user-input error (bad arity/shape) raises from
                # BOTH paths and must not permanently downgrade the
                # function to call_tf
                fallback = make_call_tf()
                out = fallback(*arrays)
                # log at the latch point only: a user-input error raises
                # from both paths (propagating above), so reaching here
                # means the translator genuinely lost a graph that TF can
                # run — keep that observable instead of masking it.
                logger.warning(
                    "native graph translation failed at run time; the "
                    "call_tf fallback succeeded and is latched for this "
                    "graph — fix the translator to regain the native "
                    "path", exc_info=True,
                )
                chosen.append(fallback)
                return out

        return fn


def placeholder_specs(graph_def, tensor_names: Sequence[str]):
    """TensorSpecs (dtype + shape, unknown dims as None) for graph inputs."""
    tf = require_tf()
    by_op = {n.name: n for n in graph_def.node}
    specs = []
    for tname in tensor_names:
        node = by_op.get(tfx.op_name(tname))
        if node is None:
            raise KeyError(f"input op {tname!r} not found in graph_def")
        dtype = tf.dtypes.as_dtype(node.attr["dtype"].type)
        shape = None
        if "shape" in node.attr and not node.attr["shape"].shape.unknown_rank:
            shape = [
                (d.size if d.size >= 0 else None)
                for d in node.attr["shape"].shape.dim
            ]
        specs.append(tf.TensorSpec(shape, dtype, name=tfx.op_name(tname)))
    return specs


class IsolatedSession:
    """A fresh tf.Graph + Session for safe, leak-free graph surgery.

    Reference parity ([U: python/sparkdl/graph/builder.py] IsolatedSession):
    a context manager whose graph/session never alias another model's.
    ``using_keras`` is accepted for API parity; Keras 3 is sessionless, so it
    only affects nothing and is recorded for introspection.
    """

    def __init__(self, graph=None, using_keras: bool = False):
        tf = require_tf()
        self._tf = tf
        self.graph = graph if graph is not None else tf.Graph()
        self.using_keras = bool(using_keras)
        self.sess = tf.compat.v1.Session(graph=self.graph)

    def __enter__(self) -> "IsolatedSession":
        self._graph_ctx = self.graph.as_default()
        self._graph_ctx.__enter__()
        return self

    def __exit__(self, *exc):
        self._graph_ctx.__exit__(*exc)
        self.sess.close()
        return False

    def run(self, fetches, feed_dict=None):
        return self.sess.run(fetches, feed_dict=feed_dict)

    def asGraphFunction(self, inputs, outputs, strip_and_freeze: bool = True) -> GraphFunction:
        """Export (a subgraph of) this session as a GraphFunction."""
        in_names = [tfx.tensor_name(i, self.graph) for i in inputs]
        out_names = [tfx.tensor_name(o, self.graph) for o in outputs]
        if strip_and_freeze:
            gdef = strip_and_freeze_upto(self.sess, self.graph, outputs)
        else:
            gdef = self.graph.as_graph_def()
        return GraphFunction(gdef, in_names, out_names)

    def importGraphFunction(self, gfn: GraphFunction, prefix: str = ""):
        """Splice a GraphFunction into this graph; returns (inputs, outputs)."""
        tf = self._tf
        with self.graph.as_default():
            scope = prefix if prefix else ""
            elems = tf.graph_util.import_graph_def(
                gfn.graph_def,
                return_elements=list(gfn.input_names) + list(gfn.output_names),
                name=scope,
            )
        n_in = len(gfn.input_names)
        return elems[:n_in], elems[n_in:]


def strip_and_freeze_upto(sess, graph, outputs):
    """Freeze variables to constants and prune nodes not feeding ``outputs``.

    Reference parity: strip_and_freeze_upto ([U: python/sparkdl/graph/
    builder.py]) — constant folding of variables plus dead-node removal, so
    the exported GraphDef is self-contained and minimal.
    """
    tf = require_tf()
    out_ops = [tfx.op_name(o) for o in outputs]
    gdef = graph.as_graph_def(add_shapes=True)
    has_variables = any(
        n.op in ("VariableV2", "Variable", "VarHandleOp") for n in gdef.node
    )
    if has_variables:
        import warnings

        with warnings.catch_warnings():
            warnings.simplefilter("ignore")  # tf1 freeze API is deprecated, not gone
            gdef = tf.compat.v1.graph_util.convert_variables_to_constants(
                sess, gdef, out_ops
            )
    else:
        gdef = tf.compat.v1.graph_util.extract_sub_graph(gdef, out_ops)
    return gdef
