"""Tensor/op name normalization utilities.

Parity with the reference's name utilities (SURVEY.md 2.9, [U:
python/sparkdl/graph/utils.py]): TF graphs address values by ``"op:idx"``
tensor names while ops are addressed bare; user-facing APIs accept either and
these helpers normalize, optionally validating against a graph.
"""

from __future__ import annotations


def op_name(name, graph=None) -> str:
    """Strip a tensor suffix: ``"dense/Relu:0" -> "dense/Relu"``.

    Accepts a string, tf.Tensor or tf.Operation. With ``graph``, validates
    that the op exists there.
    """
    raw = _as_name(name)
    base = raw.split(":")[0]
    if graph is not None:
        graph.get_operation_by_name(base)  # raises KeyError/ValueError if absent
    return base


def tensor_name(name, graph=None) -> str:
    """Canonical tensor name: append ``:0`` when no output index given."""
    raw = _as_name(name)
    parts = raw.split(":")
    if len(parts) == 1:
        out = f"{raw}:0"
    elif len(parts) == 2:
        if not parts[1].isdigit():
            raise ValueError(f"invalid tensor name {raw!r}")
        out = raw
    else:
        raise ValueError(f"invalid tensor name {raw!r}")
    if graph is not None:
        graph.get_tensor_by_name(out)
    return out


def output_index(name) -> int:
    """Output slot of a tensor reference: ``"op:2" -> 2``, bare op -> 0."""
    raw = _as_name(name)
    parts = raw.split(":")
    if len(parts) == 2 and parts[1].isdigit():
        return int(parts[1])
    if len(parts) == 1:
        return 0
    raise ValueError(f"invalid tensor name {raw!r}")


def get_tensor(name, graph):
    return graph.get_tensor_by_name(tensor_name(name))


def get_op(name, graph):
    return graph.get_operation_by_name(op_name(name))


def validated_input(name, graph) -> str:
    """Tensor name that must be produced by a graph *input* (Placeholder)."""
    t = tensor_name(name, graph)
    op = graph.get_operation_by_name(op_name(t))
    if op.type not in ("Placeholder", "PlaceholderV2", "PlaceholderWithDefault"):
        raise ValueError(
            f"input {name!r} must be a Placeholder, found op type {op.type!r}"
        )
    return t


def validated_output(name, graph) -> str:
    """Tensor name validated to exist in the graph (any producing op)."""
    return tensor_name(name, graph)


def _as_name(obj) -> str:
    if isinstance(obj, str):
        return obj
    name = getattr(obj, "name", None)
    if isinstance(name, str):
        return name
    raise TypeError(f"cannot interpret {type(obj).__name__} as a graph name")
