"""Graph ingestion & surgery — TF-artifact → JAX-callable lowering.

Parity with the reference's graph layer (SURVEY.md 2.7/2.9/2.10, [U:
python/sparkdl/graph/]): ``TFInputGraph`` (six ingestion constructors),
``GraphFunction`` + ``IsolatedSession`` (graph surgery), and the image
converter piece. The reference hands frozen GraphDefs to a TF session in the
executor JVM; here ingestion ends in a **jittable JAX function** (XLA-lowered
via ``jax2tf.call_tf``) so ingested graphs fuse, shard and run on TPU like
native JAX code.
"""

from sparkdl_tpu.graph.builder import GraphFunction, IsolatedSession
from sparkdl_tpu.graph.input import TFInputGraph

__all__ = ["GraphFunction", "IsolatedSession", "TFInputGraph"]
