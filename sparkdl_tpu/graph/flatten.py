"""Function-library inlining: flatten PartitionedCall graphs for translation.

TF2 tracing compiles every ``tf.function`` into a FunctionDef and leaves a
``PartitionedCall``/``StatefulPartitionedCall`` node (or a node whose op IS
the function name, for legacy defuns) in the calling graph. The reference
executed such graphs in a real TF session where function calls are native
(SURVEY.md 2.7/2.18); the TPU build's native translator walks a flat node
list, so call sites must be flattened first. ``inline_function_calls``
splices each called function's body into the main graph — bodies converted
through TF's ``function_def_to_graph_def`` (which resolves the
``node:out_arg:idx`` nested tensor syntax to flat ``node:idx`` form),
prefixed with the call-site name for uniqueness, arg placeholders replaced
by the call's actual inputs, and every consumer of a call output rewired to
the corresponding body tensor. Iterates to a fixpoint so nested calls
(functions calling functions) flatten too.

Functional control flow (``If``/``While`` families) is NOT a call site —
those translate directly to ``lax.cond``/``lax.while_loop`` (tf2jax.py) with
their branch bodies converted on demand.
"""

from __future__ import annotations

from typing import Any, Sequence

_CALL_OPS = ("PartitionedCall", "StatefulPartitionedCall")

#: nested-call depth guard; real model graphs nest a handful deep
_MAX_ROUNDS = 64


def _split(ref: str) -> tuple[str, int]:
    """'node:3' -> ('node', 3); 'node' -> ('node', 0) (data refs only)."""
    if ":" in ref:
        name, idx = ref.rsplit(":", 1)
        return name, int(idx)
    return ref, 0


def has_function_calls(graph_def) -> bool:
    lib = {f.signature.name for f in graph_def.library.function}
    return any(
        n.op in _CALL_OPS or n.op in lib for n in graph_def.node
    )


def _call_target(node, lib) -> "str | None":
    """Function name a node calls, or None if it is not a call site."""
    if node.op in _CALL_OPS:
        f = node.attr["f"].func.name
        return f or None
    if node.op in lib:
        return node.op
    return None


def inline_function_calls(
    graph_def, output_names: Sequence[str]
) -> tuple[Any, list[str]]:
    """Return ``(flat_graph_def, new_output_names)`` with every call site
    spliced out. No-op (same objects) when the graph has no call sites.

    Control edges: a call node's control inputs are copied onto every
    inlined body node; a control edge *to* a call node becomes control
    edges to the ops producing its return values. The native translator
    ignores control edges entirely (frozen inference graphs carry no
    state), so this only preserves ordering for any TF re-execution of the
    flattened graph.
    """
    lib = {f.signature.name: f for f in graph_def.library.function}
    if not has_function_calls(graph_def):
        return graph_def, list(output_names)

    from sparkdl_tpu.graph._tf import require_tf

    require_tf()
    from tensorflow.python.framework import (
        function_def_to_graph as _fd2g,
    )

    gd = type(graph_def)()
    gd.CopyFrom(graph_def)

    for _ in range(_MAX_ROUNDS):
        calls = [n for n in gd.node if _call_target(n, lib)]
        if not calls:
            break
        existing = {n.name for n in gd.node}
        new_nodes = []
        #: call-site name -> (output idx -> replacement data ref,
        #:                    control-target op names)
        repl: dict[str, tuple[dict[int, str], list[str]]] = {}

        for n in gd.node:
            fname = _call_target(n, lib)
            if fname is None:
                new_nodes.append(n)
                continue
            fdef = lib[fname]
            sub, nested = _fd2g.function_def_to_graph_def(fdef)
            prefix = n.name + "/"
            while any(name.startswith(prefix) for name in existing):
                prefix = prefix[:-1] + "_inlined/"
            arg_names = [a.name for a in fdef.signature.input_arg]
            data_in = [i for i in n.input if not i.startswith("^")]
            ctrl_in = [i for i in n.input if i.startswith("^")]
            if len(data_in) != len(arg_names):
                raise ValueError(
                    f"call node {n.name!r} feeds {len(data_in)} args to "
                    f"{fname!r} which declares {len(arg_names)}"
                )
            argmap = dict(zip(arg_names, data_in))

            for bn in sub.node:
                if bn.op == "Placeholder" and bn.name in argmap:
                    continue  # arg: consumers rewire to the call input
                nn = type(bn)()
                nn.CopyFrom(bn)
                nn.name = prefix + bn.name
                rewired = []
                for inp in bn.input:
                    is_ctrl = inp.startswith("^")
                    name, idx = _split(inp.lstrip("^"))
                    if name in argmap:
                        tgt = argmap[name]
                        rewired.append(
                            "^" + _split(tgt)[0] if is_ctrl else tgt
                        )
                    elif is_ctrl:
                        rewired.append("^" + prefix + name)
                    else:
                        rewired.append(f"{prefix}{name}:{idx}")
                # the call's control deps gate every inlined node
                rewired.extend(c for c in ctrl_in if c not in rewired)
                del nn.input[:]
                nn.input.extend(rewired)
                new_nodes.append(nn)
                existing.add(nn.name)

            outmap: dict[int, str] = {}
            ctrl_tgts: list[str] = []
            for i, oarg in enumerate(fdef.signature.output_arg):
                flat = nested[fdef.ret[oarg.name]]
                name, idx = _split(flat)
                if name in argmap:  # passthrough: fn returns an arg as-is
                    outmap[i] = argmap[name]
                else:
                    outmap[i] = f"{prefix}{name}:{idx}"
                ctrl_tgts.append(_split(outmap[i])[0])
            repl[n.name] = (outmap, ctrl_tgts)

        def _resolve_data(ref: str) -> str:
            # chains happen when a call's passthrough return is another
            # call's output replaced in the same round
            seen = set()
            while True:
                name, idx = _split(ref)
                entry = repl.get(name)
                if entry is None:
                    return ref
                if (name, idx) in seen:
                    raise ValueError(
                        f"cyclic call passthrough at {name!r}:{idx}"
                    )
                seen.add((name, idx))
                ref = entry[0][idx]

        def _resolve_ctrl(op: str) -> "list[str]":
            entry = repl.get(op)
            if entry is None:
                return [op]
            out = []
            for t in entry[1]:
                for r in _resolve_ctrl(t):
                    if r not in out:
                        out.append(r)
            return out

        def _rewrite(ref: str) -> "list[str]":
            if ref.startswith("^"):
                return ["^" + t for t in _resolve_ctrl(ref[1:])]
            return [_resolve_data(ref)]

        for n in new_nodes:
            rewired = []
            for inp in n.input:
                for r in _rewrite(inp):
                    # dedup CONTROL edges only — duplicate data edges are
                    # meaningful (AddN(y, y), Mul(y, y)) and must survive
                    if r.startswith("^"):
                        if r not in rewired:
                            rewired.append(r)
                    else:
                        rewired.append(r)
            del n.input[:]
            n.input.extend(rewired)

        del gd.node[:]
        gd.node.extend(new_nodes)

        output_names = [
            _rewrite(o)[0] for o in output_names
        ]
    else:
        raise ValueError(
            f"function-call nesting exceeded {_MAX_ROUNDS} inline rounds "
            "— cyclic function library?"
        )

    return gd, list(output_names)
