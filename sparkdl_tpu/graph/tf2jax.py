"""Native TF-GraphDef -> JAX lowering (no TF at execution time).

SURVEY.md §7 hard part 1 and §2 native-parity item 4: the reference ran
frozen TF graphs in a C++ TF session ([U: tensorframes] / libtensorflow);
the TPU build's equivalent is a *translator* that rebuilds the frozen graph
as JAX ops, so the result jits, fuses, shards and runs on TPU like any
other JAX code. The alternative lowering (`jax2tf.call_tf`) needs a TF
build with XLA_TPU_JIT kernels — absent from CPU-only TF wheels — so on
TPU hosts this translator IS the ingestion path; `GraphFunction.to_jax`
uses it whenever every op is covered and falls back to call_tf otherwise.

Scope: the frozen *inference* op surface (matmul/conv/BN-eval/pooling/
elementwise/shape surgery) — what Keras/TF image and tabular models freeze
to. Training ops, dynamic shapes and stateful ops are out of scope here
and rejected earlier by graph/op_surface.py.

Static-value discipline: shape-math chains (Shape -> StridedSlice -> Pack
-> Reshape) must stay concrete under jit, so Const/Shape produce numpy
values and dual-mode ops keep numpy inputs in numpy — they become trace
constants, never tracers.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Sequence

import numpy as np

from sparkdl_tpu.graph import utils as tfx


class GraphTranslationError(ValueError):
    """An op (or attr combination) outside the native translation surface."""


#: f32-contraction precision for the CURRENT translation execution
#: ("highest" = 6-pass f32 on the MXU, matches a TF session bit-for-bit-ish;
#: "default" = bf16 passes, ~6x faster, serving-grade). Set per-call by
#: translate_graph_def; contextvar so nested/jitted traces see the right one.
import contextvars

_F32_PRECISION = contextvars.ContextVar("sparkdl_tf2jax_f32_precision",
                                        default="highest")

#: function library of the graph currently being translated (name ->
#: FunctionDef) — functional control-flow translators (If/While) convert
#: their branch bodies through it on demand.
_LIBRARY: "contextvars.ContextVar[dict]" = contextvars.ContextVar(
    "sparkdl_tf2jax_library", default={})


# --------------------------------------------------------------------------
# attr plumbing
# --------------------------------------------------------------------------

_DTYPES = {
    1: np.float32, 2: np.float64, 3: np.int32, 4: np.uint8, 5: np.int16,
    6: np.int8, 7: object, 9: np.int64, 10: np.bool_, 14: "bfloat16",
    17: np.uint16, 19: "float16", 22: np.uint32, 23: np.uint64,
}


def _np_dtype(enum: int):
    dt = _DTYPES.get(enum)
    if dt is None or dt is object:
        raise GraphTranslationError(f"unsupported tensor dtype enum {enum}")
    if dt == "bfloat16":
        import jax.numpy as jnp

        return jnp.bfloat16
    if dt == "float16":
        return np.float16
    return dt


def _attr(node, name, default=None):
    if name not in node.attr:
        return default
    a = node.attr[name]
    kind = a.WhichOneof("value")
    if kind == "i":
        return int(a.i)
    if kind == "f":
        return float(a.f)
    if kind == "b":
        return bool(a.b)
    if kind == "s":
        return a.s.decode()
    if kind == "type":
        return _np_dtype(a.type)
    if kind == "list":
        if a.list.i:
            return [int(v) for v in a.list.i]
        if a.list.f:
            return [float(v) for v in a.list.f]
        if a.list.s:
            return [v.decode() for v in a.list.s]
        return []
    if kind == "shape":
        return [d.size for d in a.shape.dim]
    if kind == "func":
        return a.func.name
    return default


def _const_value(node) -> np.ndarray:
    """Materialize a Const node's tensor (TF only needed at translate time)."""
    from sparkdl_tpu.graph._tf import require_tf

    tf = require_tf()
    return np.asarray(tf.make_ndarray(node.attr["value"].tensor))


def _is_static(x) -> bool:
    return isinstance(x, (np.ndarray, np.generic, int, float, bool))


def _static(x, node, what) -> np.ndarray:
    if not _is_static(x):
        raise GraphTranslationError(
            f"node {node.name!r} ({node.op}): {what} must be statically "
            "known (a Const or shape-derived value); a traced tensor "
            "cannot drive shapes under jit"
        )
    return np.asarray(x)


# --------------------------------------------------------------------------
# translators: fn(xp, node, *inputs) -> value | tuple(values)
# xp is numpy for all-static inputs of dual-mode ops, else jax.numpy —
# keeping shape math concrete at trace time.
# --------------------------------------------------------------------------

_TRANSLATORS: dict[str, Callable] = {}
_DUAL_MODE: set[str] = set()


def _op(name, dual: bool = False):
    def wrap(fn):
        _TRANSLATORS[name] = fn
        if dual:
            _DUAL_MODE.add(name)
        return fn

    return wrap


def _register_simple():
    import jax
    import jax.numpy as jnp
    from jax import lax

    # -- passthrough -----------------------------------------------------
    for op in ("Identity", "StopGradient", "Snapshot", "PreventGradient",
               "CheckNumerics", "EnsureShape", "PlaceholderWithDefault"):
        _op(op, dual=True)(lambda xp, node, x, *rest: x)

    # -- unary elementwise ----------------------------------------------
    unary = {
        "Relu": lambda x: jnp.maximum(x, 0),
        "Relu6": lambda x: jnp.clip(x, 0, 6),
        "Elu": jax.nn.elu,
        "Selu": jax.nn.selu,
        "Sigmoid": jax.nn.sigmoid,
        "Tanh": jnp.tanh,
        "Softplus": jax.nn.softplus,
        "Softsign": jax.nn.soft_sign,
        "Exp": jnp.exp, "Log": jnp.log, "Log1p": jnp.log1p,
        "Sqrt": jnp.sqrt, "Rsqrt": lax.rsqrt, "Square": jnp.square,
        "Neg": jnp.negative, "Abs": jnp.abs, "Sign": jnp.sign,
        "Floor": jnp.floor, "Ceil": jnp.ceil, "Round": jnp.round,
        "Erf": lax.erf, "Reciprocal": jnp.reciprocal,
        "LogicalNot": jnp.logical_not,
        "Sin": jnp.sin, "Cos": jnp.cos, "Tan": jnp.tan,
        "Asin": jnp.arcsin, "Acos": jnp.arccos, "Atan": jnp.arctan,
        "Sinh": jnp.sinh, "Cosh": jnp.cosh,
        "Expm1": jnp.expm1, "Rint": jnp.rint,
        "IsFinite": jnp.isfinite, "IsNan": jnp.isnan, "IsInf": jnp.isinf,
    }
    for op, fn in unary.items():
        _op(op)(lambda xp, node, x, _fn=fn: _fn(x))

    _op("LeakyRelu")(
        lambda xp, node, x: jax.nn.leaky_relu(x, _attr(node, "alpha", 0.2))
    )
    _op("Softmax")(lambda xp, node, x: jax.nn.softmax(x, axis=-1))
    _op("LogSoftmax")(lambda xp, node, x: jax.nn.log_softmax(x, axis=-1))

    # -- binary elementwise (numpy-compatible broadcasting) --------------
    binary = {
        "Add": lambda a, b, xp: xp.add(a, b),
        "AddV2": lambda a, b, xp: xp.add(a, b),
        "Sub": lambda a, b, xp: xp.subtract(a, b),
        "Mul": lambda a, b, xp: xp.multiply(a, b),
        "Div": lambda a, b, xp: xp.divide(a, b),
        "RealDiv": lambda a, b, xp: xp.divide(a, b),
        "FloorDiv": lambda a, b, xp: xp.floor_divide(a, b),
        "FloorMod": lambda a, b, xp: xp.mod(a, b),
        "Maximum": lambda a, b, xp: xp.maximum(a, b),
        "Minimum": lambda a, b, xp: xp.minimum(a, b),
        "Pow": lambda a, b, xp: xp.power(a, b),
        "SquaredDifference": lambda a, b, xp: xp.square(
            xp.subtract(a, b)),
        "Greater": lambda a, b, xp: xp.greater(a, b),
        "GreaterEqual": lambda a, b, xp: xp.greater_equal(a, b),
        "Less": lambda a, b, xp: xp.less(a, b),
        "LessEqual": lambda a, b, xp: xp.less_equal(a, b),
        "Equal": lambda a, b, xp: xp.equal(a, b),
        "NotEqual": lambda a, b, xp: xp.not_equal(a, b),
        "LogicalAnd": lambda a, b, xp: xp.logical_and(a, b),
        "LogicalOr": lambda a, b, xp: xp.logical_or(a, b),
    }
    for op, fn in binary.items():
        _op(op, dual=True)(lambda xp, node, a, b, _fn=fn: _fn(a, b, xp))

    _op("AddN", dual=True)(
        lambda xp, node, *xs: functools.reduce(xp.add, xs)
    )
    _op("Atan2", dual=True)(
        lambda xp, node, a, b: xp.arctan2(a, b)
    )

    def _cumulative(node, x, axis, cum_fn, init):
        """TF cumsum/cumprod semantics incl. exclusive/reverse attrs.

        reverse: accumulate from the end (flip, scan, flip back);
        exclusive: shift the inclusive scan one step, seeding with the
        identity element — both applied in the flipped orientation so the
        combination matches TF ([b+c, c, 0]-style).
        """
        exclusive = _attr(node, "exclusive", False)
        reverse = _attr(node, "reverse", False)
        if reverse:
            x = jnp.flip(x, axis)
        out = cum_fn(x, axis=axis)
        if exclusive:
            n = x.shape[axis]
            seed_shape = list(x.shape)
            seed_shape[axis] = 1
            seed = jnp.full(seed_shape, init, dtype=out.dtype)
            out = jnp.concatenate(
                [seed, jax.lax.slice_in_dim(out, 0, n - 1, axis=axis)],
                axis=axis,
            )
        if reverse:
            out = jnp.flip(out, axis)
        return out

    @_op("Cumsum")
    def _cumsum(xp, node, x, axis):
        axis = int(_static(axis, node, "axis"))
        return _cumulative(node, x, axis, jnp.cumsum, 0)

    @_op("Cumprod")
    def _cumprod(xp, node, x, axis):
        axis = int(_static(axis, node, "axis"))
        return _cumulative(node, x, axis, jnp.cumprod, 1)

    @_op("OneHot")
    def _onehot(xp, node, indices, depth, on_value, off_value):
        depth = int(_static(depth, node, "depth"))
        axis = _attr(node, "axis", -1)
        oh = jax.nn.one_hot(indices, depth, axis=axis)
        # where(), not arithmetic: exact for every on/off dtype incl. bool
        return jnp.where(oh != 0, jnp.asarray(on_value),
                         jnp.asarray(off_value))

    @_op("TopKV2")
    def _topk(xp, node, x, k):
        k = int(_static(k, node, "k"))
        values, indices = jax.lax.top_k(x, k)
        return values, indices.astype(np.int32)
    @_op("Select")
    def _select_v1(xp, node, c, a, b):
        # TF Select (v1) broadcasts a rank-1 condition along the LEADING
        # axis of higher-rank operands; numpy/jnp broadcast trailing axes,
        # so reshape cond to (-1, 1, ..., 1) for that case.
        c_nd, a_nd = np.ndim(c), max(np.ndim(a), np.ndim(b))
        if c_nd == 1 and a_nd > 1:
            c = jnp.reshape(c, (-1,) + (1,) * (a_nd - 1))
        return jnp.where(c, a, b)

    _op("SelectV2")(lambda xp, node, c, a, b: jnp.where(c, a, b))
    _op("ClipByValue")(
        lambda xp, node, x, lo, hi: jnp.clip(x, lo, hi)
    )

    # -- casts -----------------------------------------------------------
    @_op("Cast", dual=True)
    def _cast(xp, node, x):
        dt = _attr(node, "DstT")
        return xp.asarray(x).astype(dt)

    # -- matmul ----------------------------------------------------------
    # f32 contractions honor the per-translation f32_precision setting:
    # "highest" (default) matches the TF session the graph is
    # oracle-checked against — TPU's default bf16 passes would silently
    # diverge; "default" trades that fidelity for ~6x faster serving.
    # bf16/f16 operands are unaffected (already low precision by choice).
    def _prec(*operands):
        if _F32_PRECISION.get() != "highest":
            return None
        return (
            jax.lax.Precision.HIGHEST
            if any(np.result_type(getattr(o, "dtype", np.float32))
                   == np.float32 for o in operands)
            else None
        )

    @_op("MatMul")
    def _matmul(xp, node, a, b):
        if _attr(node, "transpose_a", False):
            a = jnp.swapaxes(a, -1, -2)
        if _attr(node, "transpose_b", False):
            b = jnp.swapaxes(b, -1, -2)
        return jnp.matmul(a, b, precision=_prec(a, b))

    for op in ("BatchMatMul", "BatchMatMulV2", "BatchMatMulV3"):
        @_op(op)
        def _bmm(xp, node, a, b):
            if _attr(node, "adj_x", False):
                a = jnp.swapaxes(a, -1, -2)
            if _attr(node, "adj_y", False):
                b = jnp.swapaxes(b, -1, -2)
            return jnp.matmul(a, b, precision=_prec(a, b))

    @_op("Einsum")
    def _einsum(xp, node, *xs):
        return jnp.einsum(_attr(node, "equation"), *xs,
                          precision=_prec(*xs))

    # -- conv / bn / bias ------------------------------------------------
    def _conv_common(node, x, kernel, feature_group_count=1):
        # NCHW graphs (GPU-era frozen models) translate by transposing to
        # the TPU-native NHWC layout around the conv; XLA's layout
        # assignment folds the transposes, so this costs nothing at run
        # time and keeps one conv code path.
        fmt = _attr(node, "data_format", "NHWC")
        if fmt not in ("NHWC", "NCHW"):
            raise GraphTranslationError(
                f"node {node.name!r}: data_format {fmt} unsupported"
            )
        nchw = fmt == "NCHW"
        strides = _attr(node, "strides", [1, 1, 1, 1])
        dil = _attr(node, "dilations", [1, 1, 1, 1])
        hw = slice(2, 4) if nchw else slice(1, 3)
        padding = _attr(node, "padding", "VALID")
        if padding == "EXPLICIT":
            ep = _attr(node, "explicit_paddings", [])
            # explicit_paddings follows the data_format's dim order
            pads = ([(ep[4], ep[5]), (ep[6], ep[7])] if nchw
                    else [(ep[2], ep[3]), (ep[4], ep[5])])
        else:
            pads = padding
        if nchw:
            x = jnp.transpose(x, (0, 2, 3, 1))
        out = lax.conv_general_dilated(
            x, kernel,
            window_strides=strides[hw],
            padding=pads,
            rhs_dilation=dil[hw],
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=feature_group_count,
            precision=_prec(x, kernel),
        )
        return jnp.transpose(out, (0, 3, 1, 2)) if nchw else out

    @_op("Conv2D")
    def _conv2d(xp, node, x, kernel):
        return _conv_common(node, x, kernel)

    @_op("DepthwiseConv2dNative")
    def _dwconv(xp, node, x, kernel):
        kh, kw, in_ch, mult = kernel.shape
        kernel = kernel.reshape(kh, kw, 1, in_ch * mult)
        return _conv_common(node, x, kernel, feature_group_count=in_ch)

    for op in ("FusedBatchNorm", "FusedBatchNormV2", "FusedBatchNormV3"):
        @_op(op)
        def _fbn(xp, node, x, scale, offset, mean, var):
            if _attr(node, "is_training", True):
                raise GraphTranslationError(
                    f"node {node.name!r}: FusedBatchNorm in training mode "
                    "— freeze the graph for inference first"
                )
            eps = _attr(node, "epsilon", 1e-3)
            inv = lax.rsqrt(var + eps) * scale
            shift = offset - mean * inv
            if _attr(node, "data_format", "NHWC") == "NCHW":
                inv = inv.reshape(-1, 1, 1)
                shift = shift.reshape(-1, 1, 1)
            return x * inv + shift

    @_op("BiasAdd")
    def _bias(xp, node, x, b):
        if _attr(node, "data_format", "NHWC") == "NCHW":
            return x + b.reshape(1, -1, *([1] * (x.ndim - 2)))
        return x + b

    # -- pooling ---------------------------------------------------------
    def _pool(node, x, reducer, init):
        fmt = _attr(node, "data_format", "NHWC")
        if fmt not in ("NHWC", "NCHW"):
            raise GraphTranslationError(
                f"node {node.name!r}: data_format {fmt} unsupported")
        ks = _attr(node, "ksize", [1, 1, 1, 1])
        st = _attr(node, "strides", [1, 1, 1, 1])
        pad = _attr(node, "padding", "VALID")
        # window/stride attrs follow the data_format's dim order, and
        # reduce_window is layout-agnostic — no transpose needed
        return lax.reduce_window(
            x, init, reducer, tuple(ks), tuple(st), pad
        )

    @_op("MaxPool")
    def _maxpool(xp, node, x):
        return _pool(node, x, lax.max, -jnp.inf if
                     jnp.issubdtype(x.dtype, jnp.floating) else
                     jnp.iinfo(x.dtype).min)

    @_op("AvgPool")
    def _avgpool(xp, node, x):
        # TF divides by the count of non-padded cells in each window;
        # counting via a pooled all-ones constant is layout-agnostic
        # (works for NHWC and NCHW alike) and folds at compile time
        s = _pool(node, x, lax.add, 0.0 if
                  jnp.issubdtype(x.dtype, jnp.floating) else 0)
        cnt = _pool(node, jnp.ones(x.shape, x.dtype), lax.add, 0.0)
        return s / cnt

    # -- reductions ------------------------------------------------------
    reductions = {
        "Mean": jnp.mean, "Sum": jnp.sum, "Max": jnp.max, "Min": jnp.min,
        "Prod": jnp.prod, "All": jnp.all, "Any": jnp.any,
    }
    for op, fn in reductions.items():
        @_op(op)
        def _reduce(xp, node, x, axes, _fn=fn):
            axes = _static(axes, node, "reduction axes")
            # axis=() is a no-op reduction in TF (identity) and numpy/jnp
            # agree — do NOT collapse an empty list to axis=None (all axes)
            axis = tuple(int(a) for a in np.atleast_1d(axes))
            return _fn(x, axis=axis,
                       keepdims=_attr(node, "keep_dims", False))

    @_op("ArgMax")
    def _argmax(xp, node, x, axis):
        axis = int(_static(axis, node, "axis"))
        out = _attr(node, "output_type", np.int64)
        return jnp.argmax(x, axis=axis).astype(out)

    @_op("ArgMin")
    def _argmin(xp, node, x, axis):
        axis = int(_static(axis, node, "axis"))
        out = _attr(node, "output_type", np.int64)
        return jnp.argmin(x, axis=axis).astype(out)

    # -- shape surgery ---------------------------------------------------
    @_op("Shape", dual=True)
    def _shape(xp, node, x):
        if any(d is None for d in np.shape(x)):
            raise GraphTranslationError(
                f"node {node.name!r}: dynamic shape"
            )
        return np.asarray(np.shape(x),
                          _attr(node, "out_type", np.int32))

    @_op("Rank", dual=True)
    def _rank(xp, node, x):
        return np.asarray(np.ndim(x), np.int32)

    @_op("Size", dual=True)
    def _size(xp, node, x):
        return np.asarray(np.size(x),
                          _attr(node, "out_type", np.int32))

    @_op("Reshape", dual=True)
    def _reshape(xp, node, x, shape):
        shape = _static(shape, node, "shape")
        return xp.reshape(x, tuple(int(s) for s in shape))

    @_op("Squeeze", dual=True)
    def _squeeze(xp, node, x):
        dims = _attr(node, "squeeze_dims") or _attr(node, "axis")
        return xp.squeeze(x, axis=tuple(dims) if dims else None)

    @_op("ExpandDims", dual=True)
    def _expand(xp, node, x, axis):
        return xp.expand_dims(x, int(_static(axis, node, "axis")))

    @_op("ConcatV2", dual=True)
    def _concat(xp, node, *xs):
        axis = int(_static(xs[-1], node, "concat axis"))
        return xp.concatenate(xs[:-1], axis=axis)

    @_op("Concat", dual=True)
    def _concat_v1(xp, node, axis, *xs):
        return xp.concatenate(xs, axis=int(_static(axis, node, "axis")))

    @_op("Pack", dual=True)
    def _pack(xp, node, *xs):
        return xp.stack(xs, axis=_attr(node, "axis", 0))

    @_op("Unpack", dual=True)
    def _unpack(xp, node, x):
        axis = _attr(node, "axis", 0)
        n = _attr(node, "num")
        parts = xp.split(x, n, axis=axis)
        return tuple(xp.squeeze(p, axis=axis) for p in parts)

    @_op("Split")
    def _split(xp, node, axis, x):
        axis = int(_static(axis, node, "axis"))
        return tuple(jnp.split(x, _attr(node, "num_split"), axis=axis))

    @_op("SplitV")
    def _splitv(xp, node, x, sizes, axis):
        sizes = _static(sizes, node, "split sizes")
        axis = int(_static(axis, node, "axis"))
        idx = np.cumsum(sizes)[:-1]
        return tuple(jnp.split(x, [int(i) for i in idx], axis=axis))

    @_op("Transpose", dual=True)
    def _transpose(xp, node, x, perm):
        perm = _static(perm, node, "perm")
        return xp.transpose(x, tuple(int(p) for p in perm))

    for op in ("Pad", "PadV2"):
        @_op(op, dual=True)
        def _pad(xp, node, x, pads, *rest):
            pads = _static(pads, node, "paddings")
            value = rest[0] if rest else 0
            return xp.pad(x, [(int(a), int(b)) for a, b in pads],
                          constant_values=value)

    @_op("Slice", dual=True)
    def _slice(xp, node, x, begin, size):
        begin = _static(begin, node, "begin")
        size = _static(size, node, "size")
        idx = tuple(
            slice(int(b), None if int(s) == -1 else int(b) + int(s))
            for b, s in zip(begin, size)
        )
        return xp.asarray(x)[idx]

    @_op("StridedSlice", dual=True)
    def _strided(xp, node, x, begin, end, strides):
        begin = _static(begin, node, "begin")
        end = _static(end, node, "end")
        strides = _static(strides, node, "strides")
        bm = _attr(node, "begin_mask", 0)
        em = _attr(node, "end_mask", 0)
        ell = _attr(node, "ellipsis_mask", 0)
        na = _attr(node, "new_axis_mask", 0)
        sa = _attr(node, "shrink_axis_mask", 0)
        # The sparse spec maps 1:1 onto a numpy/jnp index tuple: mask bit i
        # selects how position i of the spec is interpreted; begin/end/
        # strides values at ellipsis/new-axis positions are ignored (TF
        # ignores them too).
        idx = []
        for i in range(len(begin)):
            if ell & (1 << i):
                idx.append(Ellipsis)
                continue
            if na & (1 << i):
                idx.append(None)
                continue
            if sa & (1 << i):
                idx.append(int(begin[i]))
                continue
            b = None if bm & (1 << i) else int(begin[i])
            e = None if em & (1 << i) else int(end[i])
            idx.append(slice(b, e, int(strides[i])))
        return xp.asarray(x)[tuple(idx)]

    @_op("GatherV2", dual=True)
    def _gather(xp, node, params, indices, axis):
        axis = int(_static(axis, node, "axis"))
        bd = int(_attr(node, "batch_dims", 0))
        if bd < 0:
            bd += np.ndim(indices)
        if axis < 0:
            axis += np.ndim(params)
        if bd == 0:
            return xp.take(params, indices, axis=axis)
        # batch_dims>0: the leading bd axes of params/indices are aligned
        # batches; peel them with vmap (numpy static inputs: a python map —
        # static gathers in shape-math chains are tiny)
        def _bd_gather(p, i, a, b):
            if b == 0:
                return xp.take(p, i, axis=a)
            if xp is np:
                return np.stack([
                    _bd_gather(pp, ii, a - 1, b - 1)
                    for pp, ii in zip(p, i)
                ])
            return jax.vmap(
                lambda pp, ii: _bd_gather(pp, ii, a - 1, b - 1)
            )(p, i)

        return _bd_gather(params, indices, axis, bd)

    @_op("Tile", dual=True)
    def _tile(xp, node, x, multiples):
        multiples = _static(multiples, node, "multiples")
        return xp.tile(x, tuple(int(m) for m in multiples))

    @_op("Fill", dual=True)
    def _fill(xp, node, dims, value):
        dims = _static(dims, node, "dims")
        return xp.full(tuple(int(d) for d in dims), value)

    @_op("Range", dual=True)
    def _range(xp, node, start, limit, delta):
        # dtypes follow the operands (float ranges stay float, like TF)
        return np.arange(
            _static(start, node, "start")[()],
            _static(limit, node, "limit")[()],
            _static(delta, node, "delta")[()],
        )

    @_op("ZerosLike", dual=True)
    def _zeros_like(xp, node, x):
        return xp.zeros_like(x)

    @_op("OnesLike", dual=True)
    def _ones_like(xp, node, x):
        return xp.ones_like(x)

    @_op("BroadcastTo", dual=True)
    def _broadcast_to(xp, node, x, shape):
        shape = _static(shape, node, "shape")
        return xp.broadcast_to(x, tuple(int(s) for s in shape))

    # -- functional control flow -----------------------------------------
    def _fdef_to_callable(fname: str, node) -> Callable:
        """Translate a library FunctionDef into a JAX callable over its
        args (nested call sites inside the body are inlined first)."""
        lib = _LIBRARY.get()
        fdef = lib.get(fname)
        if fdef is None:
            raise GraphTranslationError(
                f"node {node.name!r} ({node.op}): branch function "
                f"{fname!r} not in the graph's function library"
            )
        from sparkdl_tpu.graph._tf import require_tf

        require_tf()
        from tensorflow.python.framework import (
            function_def_to_graph as _fd2g,
        )
        from sparkdl_tpu.graph.flatten import inline_function_calls

        sub, nested = _fd2g.function_def_to_graph_def(
            fdef, include_library_functions=True
        )
        in_names = [f"{a.name}:0" for a in fdef.signature.input_arg]
        out_names = [
            nested[fdef.ret[a.name]] for a in fdef.signature.output_arg
        ]
        sub, out_names = inline_function_calls(sub, out_names)
        return translate_graph_def(
            sub, in_names, out_names,
            f32_precision=_F32_PRECISION.get(),
        )

    for op in ("If", "StatelessIf"):
        @_op(op)
        def _if(xp, node, cond, *args):
            then_fn = _fdef_to_callable(_attr(node, "then_branch"), node)
            else_fn = _fdef_to_callable(_attr(node, "else_branch"), node)
            operands = tuple(jnp.asarray(a) for a in args)
            out = jax.lax.cond(
                jnp.reshape(jnp.asarray(cond), ()).astype(bool),
                lambda xs: then_fn(*xs),
                lambda xs: else_fn(*xs),
                operands,
            )
            return tuple(out) if len(out) > 1 else out[0]

    for op in ("While", "StatelessWhile"):
        @_op(op)
        def _while(xp, node, *args):
            cond_fn = _fdef_to_callable(_attr(node, "cond"), node)
            body_fn = _fdef_to_callable(_attr(node, "body"), node)
            init = tuple(jnp.asarray(a) for a in args)

            out = jax.lax.while_loop(
                lambda c: jnp.reshape(cond_fn(*c)[0], ()).astype(bool),
                lambda c: tuple(body_fn(*c)),
                init,
            )
            return tuple(out) if len(out) > 1 else out[0]

    # -- image resize (the reference's in-graph decode/resize, 2.10) -----
    @_op("ResizeBilinear")
    def _resize_bilinear(xp, node, x, size):
        if _attr(node, "half_pixel_centers", False):
            return _resize(node, x, size, "bilinear")
        # TF1 legacy convention (the default in frozen TF1 graphs, the
        # reference's ingestion case): src = dst * (in/out), no half-pixel
        # shift — jax.image.resize has no mode for it, so interpolate
        # explicitly.
        return _legacy_bilinear(node, x, size)

    @_op("ResizeNearestNeighbor")
    def _resize_nn(xp, node, x, size):
        if not _attr(node, "half_pixel_centers", False):
            raise GraphTranslationError(
                f"node {node.name!r}: legacy (half_pixel_centers=False) "
                "nearest resize unsupported"
            )
        return _resize(node, x, size, "nearest")

    def _resize(node, x, size, method):
        import jax.image

        if _attr(node, "align_corners", False):
            raise GraphTranslationError(
                f"node {node.name!r}: align_corners resize unsupported"
            )
        size = _static(size, node, "size")
        h, w = int(size[0]), int(size[1])
        out = jax.image.resize(
            x.astype(jnp.float32),
            (x.shape[0], h, w, x.shape[3]), method=method,
            antialias=False,
        )
        return out.astype(x.dtype)

    def _legacy_bilinear(node, x, size):
        if _attr(node, "align_corners", False):
            raise GraphTranslationError(
                f"node {node.name!r}: align_corners resize unsupported"
            )
        size = _static(size, node, "size")
        h, w = int(size[0]), int(size[1])
        in_h, in_w = x.shape[1], x.shape[2]
        xf = x.astype(jnp.float32)

        def axis_weights(out_n, in_n):
            src = np.arange(out_n, dtype=np.float64) * (in_n / out_n)
            lo = np.floor(src).astype(np.int64)
            lo = np.clip(lo, 0, in_n - 1)
            hi = np.minimum(lo + 1, in_n - 1)
            frac = (src - lo).astype(np.float32)
            return lo, hi, frac

        y0, y1, wy = axis_weights(h, in_h)
        x0, x1, wx = axis_weights(w, in_w)
        top = jnp.take(xf, y0, axis=1)
        bot = jnp.take(xf, y1, axis=1)
        rows = top + (bot - top) * wy[None, :, None, None]
        left = jnp.take(rows, x0, axis=2)
        right = jnp.take(rows, x1, axis=2)
        out = left + (right - left) * wx[None, None, :, None]
        return out.astype(x.dtype)


_register_simple()


# --------------------------------------------------------------------------
# graph walking
# --------------------------------------------------------------------------


def untranslatable_ops(graph_def, output_names=None) -> "list[str]":
    """Ops that the native translator does NOT cover (empty list == fully
    translatable). Const/Placeholder/NoOp are structural and always fine.
    With ``output_names``, only the output-feeding subgraph is scanned, so
    unpruned graphs carrying dead nodes keep the native path.

    Call sites (PartitionedCall / direct function-name ops) count as
    translatable when their target is in the library — flatten.py inlines
    them before translation — and the scan recurses into every referenced
    function body (If/While branches, call targets) so a host-side op
    hiding inside a tf.function still surfaces here."""
    structural = {"Const", "Placeholder", "NoOp"}
    call_ops = {"PartitionedCall", "StatefulPartitionedCall"}
    lib = {f.signature.name: f for f in graph_def.library.function}
    missing: set[str] = set()
    seen_fns: set[str] = set()

    def scan(nodes):
        pending_fns = []
        for n in nodes:
            op = n.op
            if op in call_ops or op in lib:
                tgt = op if op in lib else n.attr["f"].func.name
                if tgt in lib:
                    pending_fns.append(tgt)
                else:
                    missing.add(op)
                continue
            if op not in structural and op not in _TRANSLATORS:
                missing.add(op)
            # If/While branches (and any other func-valued attr)
            for a in n.attr.values():
                if a.func.name:
                    pending_fns.append(a.func.name)
                for f in a.list.func:
                    if f.name:
                        pending_fns.append(f.name)
        for fname in pending_fns:
            if fname in lib and fname not in seen_fns:
                seen_fns.add(fname)
                scan(lib[fname].node_def)

    from sparkdl_tpu.graph.op_surface import reachable_nodes

    nodes = (graph_def.node if output_names is None
             else reachable_nodes(graph_def, output_names))
    scan(nodes)
    return sorted(missing)


def translate_graph_def(
    graph_def,
    input_names: Sequence[str],
    output_names: Sequence[str],
    f32_precision: str = "highest",
) -> Callable[..., tuple]:
    """Build ``f(*arrays) -> tuple(arrays)`` executing the frozen graph as
    native JAX ops (inputs/outputs in the given tensor-name order).

    ``f32_precision``: "highest" (default) runs f32 contractions at full
    f32 MXU precision to match the originating TF session; "default" uses
    the TPU's native bf16 passes (~6x faster contractions) for serving
    where bf16-grade features are acceptable.
    """
    import jax.numpy as jnp

    if f32_precision not in ("highest", "default"):
        raise ValueError(
            f"f32_precision must be 'highest' or 'default', "
            f"got {f32_precision!r}"
        )

    # TF2 function-call sites (PartitionedCall & friends) are flattened
    # here, so every caller gets the same contract: hand in any frozen
    # GraphDef, get a callable or a GraphTranslationError.
    from sparkdl_tpu.graph.flatten import (
        has_function_calls,
        inline_function_calls,
    )

    if has_function_calls(graph_def):
        try:
            graph_def, output_names = inline_function_calls(
                graph_def, output_names
            )
        except Exception as e:
            raise GraphTranslationError(
                f"function-library inlining failed: {e}"
            ) from e

    nodes = {n.name: n for n in graph_def.node}
    missing = untranslatable_ops(graph_def, output_names=output_names)
    if missing:
        raise GraphTranslationError(
            f"graph has ops outside the native translation surface: "
            f"{', '.join(missing)}"
        )

    in_ops = [tfx.op_name(n) for n in input_names]
    out_refs = [(tfx.op_name(n), tfx.output_index(n)) for n in output_names]

    # topo order over the subgraph feeding the outputs
    order: list[str] = []
    state: dict[str, int] = {}  # 0=visiting, 1=done

    def visit(name: str):
        stack = [(name, False)]
        while stack:
            cur, expanded = stack.pop()
            if state.get(cur) == 1:
                continue
            if expanded:
                state[cur] = 1
                order.append(cur)
                continue
            state[cur] = 0
            stack.append((cur, True))
            node = nodes.get(cur)
            if node is None:
                raise GraphTranslationError(f"missing node {cur!r}")
            for inp in node.input:
                if inp.startswith("^"):
                    continue  # control edges: frozen graphs carry no state
                dep = tfx.op_name(inp)
                if state.get(dep) != 1:
                    stack.append((dep, False))

    for name, _ in out_refs:
        visit(name)

    consts: dict[str, np.ndarray] = {}
    library = {f.signature.name: f for f in graph_def.library.function}

    def fn(*arrays) -> tuple:
        token = _F32_PRECISION.set(f32_precision)
        lib_token = _LIBRARY.set(library)
        try:
            return _run(*arrays)
        finally:
            _LIBRARY.reset(lib_token)
            _F32_PRECISION.reset(token)

    def _run(*arrays) -> tuple:
        if len(arrays) != len(in_ops):
            raise TypeError(
                f"expected {len(in_ops)} inputs, got {len(arrays)}"
            )
        env: dict[str, Any] = {}
        for op_name_, arr in zip(in_ops, arrays):
            env[op_name_] = (arr,)
        for name in order:
            if name in env:
                continue  # fed placeholder
            node = nodes[name]
            if node.op == "Const":
                if name not in consts:
                    consts[name] = _const_value(node)
                env[name] = (consts[name],)
                continue
            if node.op == "Placeholder":
                raise GraphTranslationError(
                    f"placeholder {name!r} is not in input_names"
                )
            if node.op == "NoOp":
                env[name] = ()
                continue
            ins = []
            for inp in node.input:
                if inp.startswith("^"):
                    continue
                dep, idx = tfx.op_name(inp), tfx.output_index(inp)
                ins.append(env[dep][idx])
            translator = _TRANSLATORS[node.op]
            if node.op in _DUAL_MODE and all(_is_static(i) for i in ins):
                out = translator(np, node, *ins)
            else:
                out = translator(jnp, node, *ins)
            env[name] = out if isinstance(out, tuple) else (out,)
        return tuple(
            jnp.asarray(env[name][idx]) for name, idx in out_refs
        )

    return fn
