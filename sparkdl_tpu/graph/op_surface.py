"""Supported-op surface for TF graph ingestion (SURVEY.md §7 hard part 1).

The reference executed arbitrary TF graphs in a JVM-side TF session
([U: tensorframes], SURVEY.md 2.15), so "supported" meant "any TF op with a
CPU/GPU kernel". Here ingestion lowers the frozen graph through TF's XLA
bridge into the surrounding JAX program (`GraphFunction.to_jax`), so the
real support boundary is **what XLA can compile**:

* SUPPORTED — dense math (MatMul/Conv/pooling/elementwise/reductions),
  shape manipulation with static shapes, casts, softmax/activations,
  functional control flow, XLA-compatible RNG. These lower and fuse.
* REJECTED UP FRONT (this module) — op categories that can never compile
  into a device program: host I/O and filesystem access, python callbacks,
  string processing, hash/lookup tables, queues/readers/datasets, summary
  writers, checkpoint save/restore, TF1 loop primitives, and un-frozen
  variables (freeze first; `strip_and_freeze_upto` does this).
* EVERYTHING ELSE — validated by XLA itself at first trace: ops outside
  the denylist that XLA still cannot compile fail there with the XLA
  error. The prescreen exists so the common hopeless cases fail at
  ingestion time with actionable guidance instead of deep inside a jit
  trace.

`validate_graph_def` is called by `GraphFunction.to_jax()` (pass
``validate=False`` to skip the prescreen and let XLA be the only judge).
"""

from __future__ import annotations

from typing import Any

#: exact op names that cannot lower to a TPU program
_REJECT_EXACT = {
    # python callbacks
    "PyFunc", "PyFuncStateless", "EagerPyFunc",
    # host filesystem / IO
    "ReadFile", "WriteFile", "MatchingFiles", "Print", "PrintV2", "Assert",
    # image codecs (host-side work; use sparkdl_tpu.image.imageIO /
    # native decode, then feed the decoded tensor)
    "DecodeJpeg", "DecodePng", "DecodeGif", "DecodeBmp", "DecodeImage",
    "EncodeJpeg", "EncodePng", "DecodeRaw", "DecodeCompressed",
    # checkpoint plumbing
    "Save", "SaveV2", "SaveSlices", "Restore", "RestoreV2", "RestoreSlice",
    "MergeV2Checkpoints", "ShardedFilename", "ShardedFilespec",
    # TF1 while-loop primitives (functional While/If lower fine; raw v1
    # loop graphs don't survive the XLA bridge)
    "Enter", "Exit", "NextIteration", "LoopCond", "RefEnter", "RefExit",
    # misc host-state
    "Mutex", "MutexLock", "MutexV2", "Barrier", "GetSessionHandle",
    "GetSessionTensor", "DeleteSessionTensor", "Placeholder.deprecated",
}

#: op-name prefixes for whole rejected families
_REJECT_PREFIXES = (
    "String",        # string processing has no device representation
    "Regex", "StaticRegex",
    "AsString", "DecodeBase64", "EncodeBase64", "Substr", "UnicodeDecode",
    "ParseExample", "ParseSequenceExample", "ParseSingleExample",
    "DecodeCSV", "DecodeJSONExample", "SerializeTensor", "ParseTensor",
    "LookupTable", "HashTable", "MutableHashTable", "MutableDenseHashTable",
    "InitializeTable", "AnonymousHashTable",
    "Queue", "FIFOQueue", "PaddingFIFOQueue", "RandomShuffleQueue",
    "PriorityQueue", "Reader", "WholeFileReader", "TextLineReader",
    "FixedLengthRecordReader", "TFRecordReader", "IdentityReader",
    "Iterator", "OneShotIterator", "MultiDeviceIterator", "MakeIterator",
    "AnonymousIterator", "DeserializeIterator", "SerializeIterator",
    "BoostedTrees", "TensorForest",
    "Audio", "Summary", "ScalarSummary", "HistogramSummary", "ImageSummary",
    "MergeSummary", "WriteSummary", "CreateSummary",
)

#: variables must be frozen to constants before ingestion
_VARIABLE_OPS = {
    "Variable", "VariableV2", "VarHandleOp", "ReadVariableOp",
    "AssignVariableOp", "AssignAddVariableOp", "AssignSubVariableOp",
    "ResourceGather", "ResourceScatterAdd", "TemporaryVariable",
}

#: ops that match a rejected prefix but are, in fact, device-compilable
_ALLOW_EXACT = {
    "IteratorGetNextSync",  # never seen post-freeze, but harmless
    "SummaryWriter",        # resource handle: unreachable post-freeze
}


class UnsupportedGraphOpsError(ValueError):
    """Raised at ingestion when a frozen graph contains ops that can never
    compile into the TPU program. Carries ``violations`` as a list of
    (node_name, op_name, reason)."""

    def __init__(self, violations: list[tuple[str, str, str]]):
        self.violations = violations
        shown = violations[:10]
        lines = "\n".join(
            f"  - node {name!r}: op {op!r} ({reason})"
            for name, op, reason in shown
        )
        more = (
            f"\n  ... and {len(violations) - len(shown)} more"
            if len(violations) > len(shown) else ""
        )
        super().__init__(
            f"graph contains {len(violations)} op(s) outside the "
            f"TPU-compilable surface:\n{lines}{more}\n"
            "Remedies: do host-side work (file IO, string parsing, image "
            "decode) outside the graph and feed tensors — imageIO/"
            "native decode covers the image case; freeze variables with "
            "strip_and_freeze_upto; or pass validate=False to skip this "
            "prescreen and let XLA report at first trace."
        )


def _classify(op: str) -> str | None:
    """Reason string when ``op`` is outside the surface, else None."""
    if op in _ALLOW_EXACT:
        return None
    if op in _VARIABLE_OPS:
        return "un-frozen variable; freeze to constants first"
    if op in _REJECT_EXACT:
        return "host-side / stateful: cannot lower to a device program"
    for prefix in _REJECT_PREFIXES:
        if op.startswith(prefix):
            return (
                f"'{prefix}*' family is host-side: cannot lower to a "
                "device program"
            )
    return None


def _referenced_functions(nodes) -> set[str]:
    """Function names referenced from ``nodes``' attrs (call ops like
    PartitionedCall carry them in func/list-of-func attr values)."""
    names = set()
    for n in nodes:
        for attr in n.attr.values():
            if attr.func.name:
                names.add(attr.func.name)
            for f in attr.list.func:
                if f.name:
                    names.add(f.name)
    return names


def reachable_nodes(graph_def, output_names) -> list:
    """Main-graph nodes reachable from ``output_names`` via DATA edges.

    Control edges (``^dep``) are deliberately not followed: the native
    translator ignores them (frozen graphs carry no state), and a dead
    Assert/Print hooked on only by control dependency is executable by the
    call_tf fallback anyway — scanning it would reject graphs both paths
    can in fact run. Shared with tf2jax.untranslatable_ops (single
    reachability definition for the whole ingestion stack).
    """
    by_name = {n.name: n for n in graph_def.node}
    pending = [name.split(":")[0].lstrip("^") for name in output_names]
    seen: set[str] = set()
    reached = []
    while pending:
        cur = pending.pop()
        if cur in seen:
            continue
        seen.add(cur)
        node = by_name.get(cur)
        if node is None:
            continue  # missing node: translate_graph_def reports it better
        reached.append(node)
        for inp in node.input:
            if inp.startswith("^"):
                continue
            pending.append(inp.split(":")[0])
    return reached


def scan_graph_def(
    graph_def: Any, output_names: "list[str] | None" = None
) -> list[tuple[str, str, str]]:
    """All (node_name, op, reason) violations in ``graph_def`` and in the
    function-library bodies REACHABLE from it (defun bodies can hide host
    ops). Unreachable library functions are ignored: TF2 SavedModels keep
    dead ``__inference__traced_save/restore`` machinery in the library,
    and dead save/restore ops can't hurt a program that never calls them.

    When ``output_names`` is given, the main-graph scan is likewise
    restricted to the subgraph feeding those outputs, so unpruned frozen
    GraphDefs carrying dead Assert/SaveV2/Print nodes validate the same
    way the pruned ones do (`strip_and_freeze_upto` would drop them).
    """
    violations = []

    def scan_nodes(nodes, where=""):
        for n in nodes:
            reason = _classify(n.op)
            if reason is not None:
                violations.append((where + n.name, n.op, reason))

    if output_names is not None:
        main_nodes = reachable_nodes(graph_def, output_names)
    else:
        main_nodes = list(graph_def.node)
    scan_nodes(main_nodes)

    by_name = {fn.signature.name: fn for fn in graph_def.library.function}
    pending = _referenced_functions(main_nodes)
    seen: set[str] = set()
    while pending:
        name = pending.pop()
        if name in seen:
            continue
        seen.add(name)
        fn = by_name.get(name)
        if fn is None:
            continue
        scan_nodes(fn.node_def, where=f"{name}/")
        pending |= _referenced_functions(fn.node_def) - seen
    return violations


def validate_graph_def(
    graph_def: Any, output_names: "list[str] | None" = None
) -> None:
    """Raise :class:`UnsupportedGraphOpsError` if the graph contains ops
    that can never compile; silently pass otherwise (XLA remains the final
    authority at trace time). ``output_names`` restricts the scan to the
    output-feeding subgraph."""
    violations = scan_graph_def(graph_def, output_names=output_names)
    if violations:
        raise UnsupportedGraphOpsError(violations)
