"""Gated TensorFlow import for the ingestion layer.

TF is ONLY used at ingestion time (load/freeze/lower an artifact); the hot
path is pure JAX/XLA. Everything else in the framework must work without TF
installed, so every TF touch goes through :func:`require_tf`.
"""

from __future__ import annotations

import os


def require_tf():
    """Import tensorflow (CPU-pinned, quiet) or raise a clear error."""
    os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "2")
    try:
        import tensorflow as tf
    except Exception as e:  # pragma: no cover - env without TF
        raise ImportError(
            "TensorFlow is required only for ingesting TF artifacts "
            "(TFInputGraph / GraphFunction / IsolatedSession). Install "
            "tensorflow-cpu, or use the Keras/Flax paths which do not "
            "need it."
        ) from e
    try:
        # Ingestion must never grab an accelerator TF might see.
        tf.config.set_visible_devices([], "GPU")
    except Exception:
        pass
    return tf
