"""sparkdl_tpu — TPU-native Deep Learning Pipelines.

Public surface mirrors the reference's ``sparkdl`` package (SURVEY.md 2.21,
[U: python/sparkdl/__init__.py]): the same transformer/estimator/UDF names,
re-implemented on JAX/XLA for TPU. Imports are lazy so that lightweight uses
(image IO, params) do not pull in flax/TF.
"""

import os as _os

# Keras models loaded by this framework should execute natively on JAX so
# they jit/shard like everything else (Keras 3 multi-backend). Must be set
# before the first `import keras` anywhere in the process; users can
# override by exporting KERAS_BACKEND themselves.
_os.environ.setdefault("KERAS_BACKEND", "jax")

from sparkdl_tpu.version import __version__

_LAZY = {
    # name -> module path
    "DeepImageFeaturizer": "sparkdl_tpu.transformers.named_image",
    "DeepImagePredictor": "sparkdl_tpu.transformers.named_image",
    "KerasTransformer": "sparkdl_tpu.transformers.keras_tensor",
    "DeepTextFeaturizer": "sparkdl_tpu.transformers.text",
    "DeepTextGenerator": "sparkdl_tpu.transformers.text_generator",
    "KerasImageFileTransformer": "sparkdl_tpu.transformers.keras_image",
    "TFTransformer": "sparkdl_tpu.transformers.tf_tensor",
    "TFImageTransformer": "sparkdl_tpu.transformers.tf_image",
    "KerasImageFileEstimator": "sparkdl_tpu.estimators.keras_image_file_estimator",
    "TFInputGraph": "sparkdl_tpu.graph.input",
    "GraphFunction": "sparkdl_tpu.graph.builder",
    "IsolatedSession": "sparkdl_tpu.graph.builder",
    "registerKerasImageUDF": "sparkdl_tpu.udf.keras_image_model",
    "TPURunner": "sparkdl_tpu.runner.tpu_runner",
    "HorovodRunner": "sparkdl_tpu.runner.tpu_runner",
    "ServingEngine": "sparkdl_tpu.serving.engine",
    "ContinuousGPTEngine": "sparkdl_tpu.serving.continuous",
    "imageIO": "sparkdl_tpu.image",
    "readImages": "sparkdl_tpu.image.imageIO",
    "readImagesWithCustomFn": "sparkdl_tpu.image.imageIO",
}

__all__ = sorted(_LAZY) + ["__version__"]


def __getattr__(name: str):
    if name in _LAZY:
        import importlib

        mod = importlib.import_module(_LAZY[name])
        obj = getattr(mod, name)
        globals()[name] = obj
        return obj
    raise AttributeError(f"module 'sparkdl_tpu' has no attribute {name!r}")


def __dir__():
    return __all__
