"""jax cross-version API bridging — the one place spelling drift lives.

The framework targets current jax but must run (tests and all) on older
runtimes too; every symbol whose location moved between versions gets
resolved here once, so call sites stay clean. Sibling helpers:
:func:`sparkdl_tpu.runtime.mesh.mesh_context` (``jax.set_mesh`` vs the
0.4.x Mesh context manager) and
``parallel.tensor_parallel._active_mesh`` (``get_abstract_mesh``).
"""

from __future__ import annotations

import jax

try:
    shard_map = jax.shard_map  # jax >= 0.6 top-level
except AttributeError:  # jax < 0.6
    from jax.experimental.shard_map import (  # type: ignore[no-redef]
        shard_map as _experimental_shard_map,
    )

    def shard_map(*args, **kwargs):
        # the replication-check escape hatch was renamed check_rep ->
        # check_vma with the VMA type system; call sites use the current
        # spelling, this bridge speaks the old one
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _experimental_shard_map(*args, **kwargs)

__all__ = ["shard_map"]
