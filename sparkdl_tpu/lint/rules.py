"""The project-native rules sparkdl-lint ships (ISSUE 11).

Each rule encodes one convention the codebase already relies on but no
tool enforced until now:

* ``lock-discipline`` — in classes owning a ``threading.Lock/RLock/
  Condition``, every attribute assigned under the lock must be assigned
  under it everywhere (lock-held-ness propagates through same-class
  helper calls, so ``tick() -> self._admit()`` style decomposition does
  not false-positive); plus a cross-method lock-acquisition graph that
  rejects ordering cycles (ABBA deadlocks).
* ``donation-safety`` — a buffer passed at a donated position of a
  ``chain_carry``/``jax.jit(donate_argnums=...)`` callable is DEAD after
  the call; reading it again before rebinding is the ``_owned_put``
  aliasing class of bug (PR 6) this rule exists to kill.
* ``blocking-in-hot-loop`` — ``time.sleep``, un-timed-out ``.result()``
  / ``.join()`` / ``.wait()``, and synchronous ``jax.device_get`` inside
  the engine tick/decode loops and replica worker loops (hot = the named
  loop methods plus everything they transitively call in-class).
* ``metric-drift`` — every ``sparkdl_*`` metric family must be declared
  with ONE (kind, label-set) across all call sites and appear in
  README.md/PERF.md.
* ``fault-coverage`` — every ``fault_point("x")`` site must be exercised
  by a test fault plan or run-tests.sh, every plan-named site must
  exist, and ``faults.KNOWN_SITES`` must not drift from reality.
* ``env-pin`` — direct ``os.environ``/``getenv`` reads of
  ``SPARKDL_TPU_*`` happen only inside ``resolve_pin`` or for variables
  on the documented allowlist below; pin-managed knobs NEVER read
  directly.
* ``sleep-poll`` (tests) — a ``while`` loop that ``time.sleep``-polls
  without a deadline in its condition is a flaky-soak trap; use the
  ``wait_until`` helper from conftest.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, Iterator

from sparkdl_tpu.lint.core import (
    Finding,
    Project,
    Rule,
    SourceFile,
    dotted_name,
    str_const,
)

__all__ = ["ALL_RULES"]


# ---------------------------------------------------------------------------
# shared AST utilities
# ---------------------------------------------------------------------------

#: does this attribute/name look like a mutex? (terminal segment)
_LOCKISH_RE = re.compile(r"(?:^|_)(?:lock|rlock|cv|cond|condition|mutex)$",
                         re.IGNORECASE)


def _is_lockish(expr: ast.AST) -> "str | None":
    """The dotted path of a with-item that names a lock, else None."""
    d = dotted_name(expr)
    if d is None:
        return None
    if _LOCKISH_RE.search(d.rsplit(".", 1)[-1]):
        return d
    return None


def _lock_items(node: ast.With) -> "list[str]":
    out = []
    for item in node.items:
        d = _is_lockish(item.context_expr)
        if d is not None:
            out.append(d)
    return out


def _target_paths(target: ast.AST) -> "Iterator[str]":
    """Dotted paths assigned by one assignment target (tuples flattened;
    ``x[i] = ...`` and ``x.a[i] = ...`` count as mutating ``x``/``x.a``)."""
    if isinstance(target, (ast.Tuple, ast.List)):
        for elt in target.elts:
            yield from _target_paths(elt)
        return
    if isinstance(target, ast.Starred):
        yield from _target_paths(target.value)
        return
    if isinstance(target, ast.Subscript):
        d = dotted_name(target.value)
        if d is not None:
            yield d
        return
    d = dotted_name(target)
    if d is not None:
        yield d


def _stmt_assigned_paths(stmt: ast.stmt) -> "set[str]":
    out: set[str] = set()
    if isinstance(stmt, ast.Assign):
        for t in stmt.targets:
            out.update(_target_paths(t))
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        out.update(_target_paths(stmt.target))
    return out


def _methods(cls: ast.ClassDef) -> "list[ast.FunctionDef]":
    return [n for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]


# ===========================================================================
# Rule 1: lock-discipline
# ===========================================================================


class _MethodScan:
    """Per-method facts for the lock rule."""

    def __init__(self) -> None:
        #: (attr_path, locked_lexically, line, lock_name_or_None)
        self.assignments: "list[tuple[str, bool, int, str | None]]" = []
        #: same-class method names called: (name, locked_lexically,
        #: lock_held_at_callsite_or_None)
        self.calls: "list[tuple[str, bool, str | None]]" = []
        #: lock-acquisition facts: with L1 containing (a) with L2 or
        #: (b) call to same-class method M — edges (L1, L2) / (L1, "call:M")
        self.nested: "list[tuple[str, str, int]]" = []
        #: locks this method acquires lexically anywhere
        self.acquires: "list[str]" = []


class LockDisciplineRule(Rule):
    name = "lock-discipline"
    description = (
        "attributes guarded by a class's lock must be assigned under it "
        "on every mutation path; lock acquisition order must be acyclic"
    )

    def __init__(self) -> None:
        #: canonical lock id -> {canonical lock id -> (path, line)}
        self._edges: "dict[str, dict[str, tuple[str, int]]]" = {}

    # -- per-file ------------------------------------------------------------
    def check(self, f: SourceFile) -> "Iterable[Finding]":
        findings: "list[Finding]" = []
        for node in ast.walk(f.tree):
            if isinstance(node, ast.ClassDef):
                findings.extend(self._check_class(f, node))
        return findings

    def _scan_method(self, fn: ast.FunctionDef) -> _MethodScan:
        scan = _MethodScan()

        def walk(node: ast.AST, lock_stack: "tuple[str, ...]") -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda)):
                    continue  # nested defs have their own discipline
                stack = lock_stack
                if isinstance(child, ast.With):
                    locks = _lock_items(child)
                    for lk in locks:
                        scan.acquires.append(lk)
                        if stack:
                            scan.nested.append(
                                (stack[-1], lk, child.lineno))
                        stack = stack + (lk,)
                if isinstance(child, (ast.Assign, ast.AugAssign,
                                      ast.AnnAssign)):
                    for path in _stmt_assigned_paths(child):
                        if path.startswith("self."):
                            scan.assignments.append(
                                (path, bool(stack), child.lineno,
                                 stack[-1] if stack else None))
                if isinstance(child, ast.Call):
                    d = dotted_name(child.func)
                    if d is not None and d.startswith("self.") \
                            and d.count(".") == 1:
                        meth = d.split(".", 1)[1]
                        scan.calls.append(
                            (meth, bool(stack),
                             stack[-1] if stack else None))
                        if stack:
                            scan.nested.append(
                                (stack[-1], "call:" + meth, child.lineno))
                walk(child, stack)

        walk(fn, ())
        return scan

    def _check_class(self, f: SourceFile,
                     cls: ast.ClassDef) -> "list[Finding]":
        methods = _methods(cls)
        scans: "dict[str, _MethodScan]" = {}
        for m in methods:
            if m.name in ("__init__", "__del__", "__post_init__"):
                continue
            scans[m.name] = self._scan_method(m)
        if not scans:
            return []

        # -- lock-held propagation: a method whose every same-class call
        # site is lock-held is itself lock-held (tick() -> _admit()),
        # carrying the lock its callers held so its OWN assignments
        # guard their attributes like lexically-locked ones do.
        locks_seen = [lk for s in scans.values() for lk in s.acquires]
        default_lock = locks_seen[0] if locks_seen else "self._lock"
        held: "dict[str, str]" = {
            m: default_lock for m in scans if m.endswith("_locked")}
        changed = True
        while changed:
            changed = False
            for scan_name in scans:
                if scan_name in held:
                    continue
                effective: "list[str | None]" = []
                for other_name, other in scans.items():
                    for meth, locked, lock_at_site in other.calls:
                        if meth != scan_name:
                            continue
                        if locked:
                            effective.append(lock_at_site)
                        elif other_name in held:
                            effective.append(held[other_name])
                        else:
                            effective.append(None)
                if effective and all(e is not None for e in effective):
                    held[scan_name] = effective[0] or default_lock
                    changed = True

        guarded: "dict[str, str]" = {}  # attr -> lock name it is seen under
        for scan_name, scan in scans.items():
            ambient = held.get(scan_name)
            for path, locked, _line, lock in scan.assignments:
                if locked and lock is not None:
                    guarded.setdefault(path, lock)
                elif ambient is not None:
                    guarded.setdefault(path, ambient)

        findings: "list[Finding]" = []
        for scan_name, scan in scans.items():
            if scan_name in held:
                continue
            for path, locked, line, _lock in scan.assignments:
                if not locked and path in guarded:
                    findings.append(Finding(
                        self.name, f.rel, line,
                        f"{cls.name}.{scan_name} assigns '{path}' outside "
                        f"'with {guarded[path]}' but other code paths "
                        "assign it under that lock — hold the lock, or "
                        "suppress with the reason it is safe here",
                    ))

        # -- acquisition-order edges (cycle check runs in finalize) ----------
        def canon(lock: str) -> str:
            # file-qualified: object identity across modules is not
            # statically resolvable, and merging same-named classes
            # (two `Pool._lock`s in different files) would fabricate
            # phantom ABBA cycles — cycles are therefore detected
            # within one module's lock set, the scope the graph can
            # actually reason about
            if lock.startswith("self."):
                return f"{f.rel}:{cls.name}.{lock[5:]}"
            return f"{f.rel}:{lock}"  # global or foreign-object lock

        acquires_of = {name: set(s.acquires) for name, s in scans.items()}
        for scan_name, scan in scans.items():
            for outer, inner, line in scan.nested:
                if inner.startswith("call:"):
                    meth = inner[5:]
                    for lk in acquires_of.get(meth, ()):
                        if lk != outer:
                            self._add_edge(canon(outer), canon(lk),
                                           f.rel, line)
                elif inner != outer:
                    self._add_edge(canon(outer), canon(inner), f.rel, line)
        return findings

    def _add_edge(self, a: str, b: str, path: str, line: int) -> None:
        self._edges.setdefault(a, {}).setdefault(b, (path, line))

    # -- whole-project: cycle detection --------------------------------------
    def finalize(self, project: Project) -> "Iterable[Finding]":
        findings: "list[Finding]" = []
        color: "dict[str, int]" = {}  # 0 unvisited / 1 in-stack / 2 done
        stack: "list[str]" = []

        def visit(node: str) -> None:
            color[node] = 1
            stack.append(node)
            for nxt, (path, line) in sorted(
                    self._edges.get(node, {}).items()):
                c = color.get(nxt, 0)
                if c == 0:
                    visit(nxt)
                elif c == 1:
                    cycle = stack[stack.index(nxt):] + [nxt]
                    findings.append(Finding(
                        self.name, path, line,
                        "lock acquisition cycle: "
                        + " -> ".join(cycle)
                        + " (ABBA deadlock risk; pick one global order)",
                    ))
            stack.pop()
            color[node] = 2

        for node in sorted(self._edges):
            if color.get(node, 0) == 0:
                visit(node)
        return findings


# ===========================================================================
# Rule 2: donation-safety
# ===========================================================================


def _donated_positions(call: ast.Call) -> "tuple[int, ...] | None":
    """Donated argument indices if ``call`` builds a donating jit."""
    fn = dotted_name(call.func)
    if fn in ("chain_carry", "dispatch.chain_carry"):
        for kw in call.keywords:
            if kw.arg == "donate" and isinstance(kw.value, ast.Constant) \
                    and kw.value.value is False:
                return None
        return (0,)
    if fn in ("jax.jit", "jit", "functools.partial", "partial"):
        # functools.partial(jax.jit, donate_argnums=...) used as a
        # decorator carries the same kwarg; plain partials of other
        # functions fall through (no donate_argnums -> None)
        if fn in ("functools.partial", "partial"):
            if not call.args or dotted_name(call.args[0]) not in (
                    "jax.jit", "jit"):
                return None
        for kw in call.keywords:
            if kw.arg in ("donate_argnums", "donate_argnames"):
                v = kw.value
                if isinstance(v, ast.Constant) and isinstance(
                        v.value, int):
                    return (v.value,)
                if isinstance(v, (ast.Tuple, ast.List)):
                    out = []
                    for elt in v.elts:
                        if isinstance(elt, ast.Constant) and isinstance(
                                elt.value, int):
                            out.append(elt.value)
                    return tuple(out) if out else None
                return ()  # dynamic spec: donation exists, args unknown
    return None


def _iter_same_scope(node: ast.AST) -> "Iterator[ast.AST]":
    """Lexical-order walk that does NOT descend into function/lambda
    bodies — those are their own execution scopes (a call inside
    ``def run_chain`` is not part of the enclosing statement's flow;
    each def gets its own scan when the rule visits it)."""
    yield node
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.Lambda)):
        return
    for child in ast.iter_child_nodes(node):
        yield from _iter_same_scope(child)


def _calls_in(expr: ast.AST) -> "Iterator[ast.Call]":
    for n in _iter_same_scope(expr):
        if isinstance(n, ast.Call):
            yield n


def _iter_stmt_level(node: ast.AST) -> "Iterator[ast.AST]":
    """Walk a statement WITHOUT descending into nested statements or
    function/lambda bodies: a call inside `if cond: x = f(x)` belongs to
    the Assign (where the rebind idiom is judged), never to the If."""
    yield node
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.Lambda)):
        return
    for child in ast.iter_child_nodes(node):
        if isinstance(child, ast.stmt):
            continue
        yield from _iter_stmt_level(child)


def _calls_at_stmt_level(stmt: ast.stmt) -> "Iterator[ast.Call]":
    for n in _iter_stmt_level(stmt):
        if isinstance(n, ast.Call):
            yield n


class DonationSafetyRule(Rule):
    name = "donation-safety"
    description = (
        "a name passed at a donated position of a chain_carry/"
        "jit(donate_argnums=...) callable must be rebound before its "
        "next read — the device buffer is dead after the call"
    )

    def check(self, f: SourceFile) -> "Iterable[Finding]":
        findings: "list[Finding]" = []
        #: module namespace: decorated def names (any nesting — the
        #: engine pattern defines them inside __init__) + module-level
        #: bindings. "self.<attr>" bindings are collected PER CLASS so
        #: two classes reusing an attribute name never cross-contaminate,
        #: and bare-name bindings inside function bodies are collected
        #: per scope in _check_fn.
        module_donated: "dict[str, tuple[int, ...]]" = {}

        for node in ast.walk(f.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if isinstance(dec, ast.Call):
                        pos = _donated_positions(dec)
                        if pos:
                            module_donated[node.name] = pos
        for stmt in getattr(f.tree, "body", ()):  # module-level bindings
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
                target = dotted_name(stmt.targets[0])
                if target is not None:
                    pos = self._binding_positions(stmt, module_donated)
                    if pos:
                        module_donated[target] = pos

        #: id(fn) -> the namespace its class provides (deepest class
        #: wins: ast.walk is breadth-first, inner classes overwrite)
        fn_scope: "dict[int, dict[str, tuple[int, ...]]]" = {}
        for cls in [n for n in ast.walk(f.tree)
                    if isinstance(n, ast.ClassDef)]:
            class_donated = dict(module_donated)
            for node in ast.walk(cls):
                if isinstance(node, ast.Assign) and len(
                        node.targets) == 1:
                    target = dotted_name(node.targets[0])
                    if target is not None and target.startswith("self."):
                        pos = self._binding_positions(node, class_donated)
                        if pos:
                            class_donated[target] = pos
            for node in ast.walk(cls):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    fn_scope[id(node)] = class_donated

        for node in ast.walk(f.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                findings.extend(self._check_fn(
                    f, node, fn_scope.get(id(node), module_donated)))
        return findings

    @staticmethod
    def _binding_positions(stmt: ast.Assign,
                           known: "dict[str, tuple[int, ...]]"
                           ) -> "tuple[int, ...] | None":
        """Donated positions if this assignment binds a donating
        callable (a chain_carry/jit(donate_argnums=...) call, possibly
        inside an IfExp, or an alias of a known donated def)."""
        for call in _calls_in(stmt.value):
            pos = _donated_positions(call)
            if pos:
                return pos
        alias = dotted_name(stmt.value)
        if alias is not None:
            return known.get(alias)
        return None

    def _check_fn(self, f: SourceFile, fn: ast.FunctionDef,
                  global_donated: "dict[str, tuple[int, ...]]"
                  ) -> "list[Finding]":
        findings: "list[Finding]" = []
        donated = dict(global_donated)

        def scan_body(body: "list[ast.stmt]",
                      loop_bodies: "list[list[ast.stmt]]") -> None:
            for i, stmt in enumerate(body):
                # local (re)bindings first: `chained = chain_carry(...)`
                # arms the name; rebinding it to anything else disarms
                # (per-scope — sibling functions never see it)
                if isinstance(stmt, ast.Assign) and len(
                        stmt.targets) == 1:
                    target = dotted_name(stmt.targets[0])
                    if target is not None and not target.startswith(
                            "self."):
                        pos = self._binding_positions(stmt, donated)
                        if pos:
                            donated[target] = pos
                        else:
                            donated.pop(target, None)
                for call in _calls_at_stmt_level(stmt):
                    callee = dotted_name(call.func)
                    if callee not in donated:
                        continue
                    for pos in donated[callee]:
                        if pos >= len(call.args):
                            continue
                        path = dotted_name(call.args[pos])
                        if path is None or path == "self":
                            continue  # temporaries can't be re-read
                        self._check_use_after(
                            f, findings, callee, path, stmt,
                            body[i + 1:], loop_bodies, call)
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef, ast.ClassDef)):
                    continue  # own scope: scanned by its own pass
                # recurse into compound statements, tracking loop bodies
                for field in ("body", "orelse", "finalbody"):
                    sub = getattr(stmt, field, None)
                    if isinstance(sub, list) and sub and isinstance(
                            sub[0], ast.stmt):
                        inner_loops = loop_bodies
                        if isinstance(stmt, (ast.For, ast.While)) \
                                and field == "body":
                            inner_loops = loop_bodies + [sub]
                        scan_body(sub, inner_loops)
                for handler in getattr(stmt, "handlers", ()):
                    scan_body(handler.body, loop_bodies)

        scan_body(fn.body, [])
        return findings

    def _check_use_after(self, f: SourceFile,
                         findings: "list[Finding]", callee: str,
                         path: str, stmt: ast.stmt,
                         rest: "list[ast.stmt]",
                         loop_bodies: "list[list[ast.stmt]]",
                         call: ast.Call) -> None:
        # rebound by the very statement that consumed it? (the idiom:
        # ``state, out = chained(state, xs)``)
        if path in _stmt_assigned_paths(stmt):
            return
        # first event on `path` in the following sibling statements
        for later in rest:
            ev = self._first_event(later, path)
            if ev == "store":
                return
            if ev is not None:
                findings.append(Finding(
                    self.name, f.rel, ev,
                    f"'{path}' was donated to {callee} at line "
                    f"{stmt.lineno} and is read again here before being "
                    "rebound — the donated buffer is dead after "
                    "dispatch; rebind the result or copy first",
                ))
                return
        # loop wrap-around: the call statement did not rebind the name,
        # so unless SOME statement in the enclosing loop body stores it,
        # the call's own argument load reads a dead buffer on the next
        # iteration
        for loop_body in loop_bodies:
            stored = any(path in _stmt_assigned_paths(other)
                         for other in loop_body)
            if not stored:
                findings.append(Finding(
                    self.name, f.rel, stmt.lineno,
                    f"'{path}' is donated to {callee} inside a loop "
                    "and never rebound in the loop body — the next "
                    "iteration reads a dead buffer",
                ))
                return

    def _first_event(self, stmt: ast.stmt, path: str) -> "int | str | None":
        """'store' if the first lexical occurrence of ``path`` in ``stmt``
        is an assignment target; the line number if it is a read; None
        if it does not occur."""
        stores = _stmt_assigned_paths(stmt)
        for node in _iter_same_scope(stmt):
            d = dotted_name(node)
            if d != path:
                continue
            ctx = getattr(node, "ctx", None)
            if isinstance(ctx, (ast.Store, ast.Del)):
                return "store"
            if isinstance(ctx, ast.Load):
                # `x = f(x)` loads then stores: count as store
                if path in stores:
                    return "store"
                return node.lineno
        return None


# ===========================================================================
# Rule 3: blocking-in-hot-loop
# ===========================================================================

#: the loop methods that must never block unboundedly; everything they
#: transitively call in-class inherits hotness
HOT_METHOD_NAMES = ("_loop", "_watchdog_loop", "tick", "_run_loop")


class BlockingInHotLoopRule(Rule):
    name = "blocking-in-hot-loop"
    description = (
        "no time.sleep, un-timed-out .result()/.join()/.wait(), or "
        "synchronous jax.device_get inside engine tick/decode/worker "
        "loops (transitively through same-class helpers)"
    )

    def check(self, f: SourceFile) -> "Iterable[Finding]":
        findings: "list[Finding]" = []
        for node in ast.walk(f.tree):
            if isinstance(node, ast.ClassDef):
                findings.extend(self._check_class(f, node))
        return findings

    def _check_class(self, f: SourceFile,
                     cls: ast.ClassDef) -> "list[Finding]":
        methods = {m.name: m for m in _methods(cls)}
        hot = {n for n in methods if n in HOT_METHOD_NAMES}
        if not hot:
            return []
        # transitive closure over same-class calls
        changed = True
        while changed:
            changed = False
            for name in list(hot):
                for node in ast.walk(methods[name]):
                    if isinstance(node, ast.Call):
                        d = dotted_name(node.func)
                        if d is not None and d.startswith("self."):
                            callee = d.split(".")[1]
                            if callee in methods and callee not in hot:
                                hot.add(callee)
                                changed = True
        findings: "list[Finding]" = []
        for name in sorted(hot):
            findings.extend(self._check_hot_fn(f, cls, methods[name]))
        return findings

    def _check_hot_fn(self, f: SourceFile, cls: ast.ClassDef,
                      fn: ast.FunctionDef) -> "list[Finding]":
        findings: "list[Finding]" = []
        where = f"{cls.name}.{fn.name} (hot loop)"
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            d = dotted_name(node.func)
            if d is None:
                continue
            leaf = d.rsplit(".", 1)[-1]
            has_timeout = bool(node.args) or any(
                kw.arg in ("timeout", "timeout_s", None)
                for kw in node.keywords)
            if d in ("time.sleep", "sleep"):
                findings.append(Finding(
                    self.name, f.rel, node.lineno,
                    f"time.sleep in {where}: a sleeping engine thread "
                    "stalls every rider — use a timed condition wait or "
                    "move the wait out of the loop"))
            elif leaf in ("result", "join", "wait") and "." in d \
                    and not has_timeout:
                findings.append(Finding(
                    self.name, f.rel, node.lineno,
                    f"un-timed-out .{leaf}() in {where}: if the producer "
                    "dies this wedges the loop forever — pass a timeout "
                    "(or suppress with the invariant that guarantees "
                    "resolution)"))
            elif d in ("jax.device_get", "device_get"):
                findings.append(Finding(
                    self.name, f.rel, node.lineno,
                    f"synchronous jax.device_get in {where}: blocks the "
                    "loop on a D2H copy — use runtime.completion."
                    "start_fetch and collect behind the next dispatch"))
        return findings


# ===========================================================================
# Rule 4: metric-drift
# ===========================================================================


class MetricDriftRule(Rule):
    name = "metric-drift"
    description = (
        "every sparkdl_* metric family keeps one (kind, label-set) "
        "across all declaration sites and is documented in README/PERF"
    )
    scope = "all"  # tests may re-declare families; they must agree too

    def __init__(self) -> None:
        #: name -> list of (kind, labels, path, line, is_test)
        self._decls: "dict[str, list]" = {}

    def check(self, f: SourceFile) -> "Iterable[Finding]":
        consts: "dict[str, str]" = {}
        for node in ast.walk(f.tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                v = str_const(node.value)
                if v is not None:
                    consts.setdefault(node.targets[0].id, v)
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.Call):
                continue
            if not isinstance(node.func, ast.Attribute):
                continue
            kind = node.func.attr
            if kind not in ("counter", "gauge", "histogram"):
                continue
            if not node.args:
                continue
            name = str_const(node.args[0])
            if name is None and isinstance(node.args[0], ast.Name):
                name = consts.get(node.args[0].id)
            if name is None or not name.startswith("sparkdl_"):
                continue
            labels: "tuple[str, ...] | None" = ()
            for kw in node.keywords:
                if kw.arg == "labels":
                    if isinstance(kw.value, (ast.Tuple, ast.List)):
                        vals = [str_const(e) for e in kw.value.elts]
                        labels = (tuple(v for v in vals if v is not None)
                                  if all(v is not None for v in vals)
                                  else None)
                    else:
                        labels = None  # dynamic: skip consistency check
            self._decls.setdefault(name, []).append(
                (kind, labels, f.rel, node.lineno, f.is_test))
        return ()

    def finalize(self, project: Project) -> "Iterable[Finding]":
        findings: "list[Finding]" = []
        for name, decls in sorted(self._decls.items()):
            shapes = {(kind, labels) for kind, labels, *_ in decls
                      if labels is not None}
            if len(shapes) > 1:
                detail = "; ".join(
                    f"{kind} labels={list(labels)} at {path}:{line}"
                    for kind, labels, path, line, _t in decls
                    if labels is not None)
                for _kind, labels, path, line, _t in decls:
                    if labels is None:
                        continue
                    findings.append(Finding(
                        self.name, path, line,
                        f"metric family '{name}' is declared with "
                        f"conflicting shapes across call sites ({detail})"
                        " — the registry will raise at runtime when both "
                        "paths run; unify the declaration"))
            prod = [d for d in decls if not d[4]]
            if prod and name not in project.docs_text:
                _kind, _labels, path, line, _t = prod[0]
                findings.append(Finding(
                    self.name, path, line,
                    f"metric family '{name}' is not documented — add it "
                    "to the README metrics catalog (or PERF.md)"))
        return findings


# ===========================================================================
# Rule 5: fault-coverage
# ===========================================================================

_PLAN_ENV = "SPARKDL_TPU_FAULT_PLAN"
#: run-tests.sh / shell: SPARKDL_TPU_FAULT_PLAN="..." or ='...'
_SH_PLAN_RE = re.compile(_PLAN_ENV + r"""=["']([^"']+)["']""")


def _plan_sites(plan: str) -> "Iterator[str]":
    for entry in plan.split(";"):
        entry = entry.strip()
        if not entry or entry.startswith("seed="):
            continue
        site = re.split(r"[:@%]", entry, 1)[0].strip()
        if site:
            yield site


class FaultCoverageRule(Rule):
    name = "fault-coverage"
    description = (
        "every fault_point site is exercised by a test plan or "
        "run-tests.sh; every plan-named site exists; faults.KNOWN_SITES "
        "does not drift"
    )
    scope = "all"

    def __init__(self) -> None:
        #: site -> (path, line); sites ending '*' are f-string prefixes
        self._sites: "dict[str, tuple[str, int]]" = {}
        #: sites referenced by plans/direct hits in TESTS + aux
        self._exercised: "set[str]" = set()
        #: (site, path, line) from every plan string (existence check)
        self._plan_refs: "list[tuple[str, str, int]]" = []
        #: KNOWN_SITES literal as found in faults.py
        self._known_sites: "tuple[set[str], str, int] | None" = None

    def check(self, f: SourceFile) -> "Iterable[Finding]":
        rel = f.rel.replace("\\", "/")
        if "sparkdl_tpu/lint/" in rel or rel.startswith("lint/"):
            return ()  # the linter's own metadata strings are not plans
        is_faults_mod = rel.endswith("reliability/faults.py")
        for node in ast.walk(f.tree):
            if isinstance(node, ast.Assign) and is_faults_mod:
                for t in node.targets:
                    if dotted_name(t) == "KNOWN_SITES" and isinstance(
                            node.value, (ast.Tuple, ast.List)):
                        vals = {str_const(e) for e in node.value.elts}
                        self._known_sites = (
                            {v for v in vals if v}, f.rel, node.lineno)
            if not isinstance(node, ast.Call):
                continue
            d = dotted_name(node.func)
            if d is None:
                continue
            leaf = d.rsplit(".", 1)[-1]
            if leaf == "fault_point" and node.args:
                site = str_const(node.args[0])
                if site is None and isinstance(node.args[0], ast.JoinedStr):
                    site = self._fstring_prefix(node.args[0])
                if site is None:
                    continue
                if f.is_test:
                    self._exercised.add(site.rstrip("*").rstrip("."))
                elif not is_faults_mod:
                    self._sites.setdefault(site, (f.rel, node.lineno))
            elif leaf in ("inject", "arm", "parse") and node.args:
                plan = str_const(node.args[0])
                if plan is not None:
                    self._collect_plan(plan, f, node.lineno,
                                       exercised=f.is_test)
            elif leaf in ("setenv",) and len(node.args) >= 2:
                key = str_const(node.args[0])
                if key is None:
                    # monkeypatch.setenv(faults.ENV_VAR, ...) — the
                    # constant's dotted spelling names the plan var
                    kd = dotted_name(node.args[0])
                    if kd is not None and kd.rsplit(".", 1)[-1] == \
                            "ENV_VAR":
                        key = _PLAN_ENV
                if key == _PLAN_ENV:
                    plan = str_const(node.args[1])
                    if plan is not None:
                        self._collect_plan(plan, f, node.lineno,
                                           exercised=True)
        # env dict literals / subscript assignments naming the plan var
        for node in ast.walk(f.tree):
            if isinstance(node, ast.Dict):
                for k, v in zip(node.keys, node.values):
                    if k is not None and str_const(k) == _PLAN_ENV:
                        plan = str_const(v)
                        if plan is not None:
                            self._collect_plan(plan, f, node.lineno,
                                               exercised=f.is_test)
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Subscript) and str_const(
                            t.slice) == _PLAN_ENV:
                        plan = str_const(node.value)
                        if plan is not None:
                            self._collect_plan(plan, f, node.lineno,
                                               exercised=f.is_test)
        return ()

    @staticmethod
    def _fstring_prefix(node: ast.JoinedStr) -> "str | None":
        if node.values and isinstance(node.values[0], ast.Constant):
            return str(node.values[0].value) + "*"
        return None

    def _collect_plan(self, plan: str, f: SourceFile, line: int,
                      exercised: bool) -> None:
        for site in _plan_sites(plan):
            self._plan_refs.append((site, f.rel, line))
            if exercised:
                self._exercised.add(site)

    def finalize(self, project: Project) -> "Iterable[Finding]":
        for name, (path, text) in project.aux.items():
            for m in _SH_PLAN_RE.finditer(text):
                line = text[:m.start()].count("\n") + 1
                for site in _plan_sites(m.group(1)):
                    self._plan_refs.append((site, path, line))
                    self._exercised.add(site)

        findings: "list[Finding]" = []
        # Coverage is a WHOLE-TREE property: a package-only scan has no
        # test plans in scope and a tests-only scan has no production
        # sites, so either direction of the check would report false
        # drift. Both cross-set checks require both sides scanned (the
        # run-tests.sh gate and bench.py always pass both dirs); the
        # per-file plan parsing above still runs on any scope.
        scanned_tests = any(f.is_test for f in project.files)
        scanned_prod = any(not f.is_test for f in project.files)

        def matches(site: str, ref: str) -> bool:
            if site.endswith("*"):
                return ref.startswith(site[:-1]) or \
                    site[:-1].rstrip(".") == ref
            return site == ref

        if scanned_tests:
            for site, (path, line) in sorted(self._sites.items()):
                hit = any(matches(site, ref) or matches(ref + "*", site)
                          for ref in self._exercised)
                if not hit:
                    findings.append(Finding(
                        self.name, path, line,
                        f"fault site '{site}' is exercised by no test "
                        "fault plan and no run-tests.sh plan — add a "
                        "chaos/unit plan hitting it (an unexercised "
                        "site is dead reliability surface)"))
        if scanned_prod:
            for ref, path, line in sorted(set(self._plan_refs)):
                known = any(matches(site, ref) for site in self._sites)
                if not known:
                    findings.append(Finding(
                        self.name, path, line,
                        f"fault plan names site '{ref}' but no "
                        "fault_point(...) with that name exists in "
                        "production code — the rule would never fire"))
        if self._known_sites is not None:
            known, path, line = self._known_sites
            for site in sorted(self._sites):
                base = site.rstrip("*").rstrip(".")
                if site not in known and base not in known:
                    findings.append(Finding(
                        self.name, path, line,
                        f"faults.KNOWN_SITES is missing site '{base}' — "
                        "the catalog drifted from the fault_point calls "
                        "in production code"))
        return findings


# ===========================================================================
# Rule 6: env-pin
# ===========================================================================

#: SPARKDL_TPU_* vars with a resolve_pin contract: NEVER read directly.
PIN_MANAGED = {
    "SPARKDL_TPU_PREFETCH",
    "SPARKDL_TPU_PREFILL_CHUNK",
    "SPARKDL_TPU_REPLICAS",
}

#: Documented direct-read allowlist (README "Static analysis"): process
#: bootstrap/infra switches read once at import or inside their own
#: dedicated resolver, not tunable pipeline knobs.
ENV_ALLOWLIST = {
    "SPARKDL_TPU_FAULT_PLAN": "parsed once at import so subprocess "
                              "ranks inherit the plan",
    "SPARKDL_TPU_RETRY_BUDGET": "process-wide budget sized once at "
                                "first use",
    "SPARKDL_TPU_TRACE": "tracing on/off switch, read at import",
    "SPARKDL_TPU_METRICS_PORT": "exporter opt-in, read at server start",
    "SPARKDL_TPU_HOST_ID": "fabric host identity (a k8s pod name), "
                           "read by default_host_id() at engine "
                           "construction — infra identity, not a "
                           "tunable knob",
    "SPARKDL_TPU_PROFILE": "bench profiling switch",
    "SPARKDL_TPU_PROFILE_DIR": "bench profiling output dir",
    "SPARKDL_TPU_PROFILE_HZ": "bench profiling sample rate",
    "SPARKDL_TPU_PROFILER_PORT": "per-rank profiler port convention",
    "SPARKDL_TPU_SKIP_HEALTH_CHECK": "preflight escape hatch",
    "SPARKDL_TPU_DISABLE_NATIVE": "native-extension kill switch",
    "SPARKDL_TPU_AUTOTUNE": "autotuner default, read by "
                            "autotune_enabled()",
    "SPARKDL_TPU_FLIGHT_DIR": "flight-recorder output dir",
    "SPARKDL_TPU_FLIGHT_EVENTS": "flight-recorder ring size",
    "SPARKDL_TPU_FLIGHT_MIN_INTERVAL_S": "flight-recorder rate limit",
    "SPARKDL_TPU_FETCH_THREADS": "readback fallback pool size, sized "
                                 "once at first use",
    "SPARKDL_TPU_CHAIN_K": "resolved by default_chain_k(), the chain-K "
                           "pin resolver (pre-dates resolve_pin; "
                           "ScanChainer registers it pinned)",
    "SPARKDL_TPU_DISPATCH_GAP_MS": "calibration override read by "
                                   "ChainPolicy.gap()",
}

#: functions whose body owns the env contract
PIN_RESOLVER_FUNCS = {"resolve_pin"}


class EnvPinRule(Rule):
    name = "env-pin"
    description = (
        "direct os.environ/getenv reads of SPARKDL_TPU_* are allowed "
        "only inside resolve_pin or for documented-allowlist variables"
    )

    def check(self, f: SourceFile) -> "Iterable[Finding]":
        findings: "list[Finding]" = []
        consts: "dict[str, str]" = {}
        for node in ast.walk(f.tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                t = node.targets[0]
                v = str_const(node.value)
                if isinstance(t, ast.Name) and v is not None:
                    consts.setdefault(t.id, v)

        def resolve(arg: ast.AST) -> "str | None":
            v = str_const(arg)
            if v is not None:
                return v
            d = dotted_name(arg)
            if d is not None:
                return consts.get(d.rsplit(".", 1)[-1])
            return None

        def scan(node: ast.AST, fn_stack: "tuple[str, ...]") -> None:
            for child in ast.iter_child_nodes(node):
                stack = fn_stack
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    stack = fn_stack + (child.name,)
                var, line = self._env_read(child, resolve)
                if var is not None and var.startswith("SPARKDL_TPU_"):
                    findings.extend(self._judge(f, var, line, stack))
                scan(child, stack)

        scan(f.tree, ())
        return findings

    @staticmethod
    def _env_read(node: ast.AST, resolve) -> "tuple[str | None, int]":
        if isinstance(node, ast.Call):
            d = dotted_name(node.func)
            if d in ("os.environ.get", "environ.get", "os.getenv",
                     "getenv") and node.args:
                return resolve(node.args[0]), node.lineno
        if isinstance(node, ast.Subscript) and isinstance(
                getattr(node, "ctx", None), ast.Load):
            if dotted_name(node.value) in ("os.environ", "environ"):
                return resolve(node.slice), node.lineno
        return None, 0

    def _judge(self, f: SourceFile, var: str, line: int,
               fn_stack: "tuple[str, ...]") -> "Iterator[Finding]":
        if any(fn in PIN_RESOLVER_FUNCS for fn in fn_stack):
            return
        if var in PIN_MANAGED:
            yield Finding(
                self.name, f.rel, line,
                f"direct read of pin-managed {var} — this knob's "
                "explicit-arg/env conflict contract lives in "
                "ingest.pipeline.resolve_pin; route the read through it")
        elif var not in ENV_ALLOWLIST:
            yield Finding(
                self.name, f.rel, line,
                f"direct read of {var} outside resolve_pin and the "
                "documented allowlist — give the knob a resolve_pin "
                "contract, or add it to lint.rules.ENV_ALLOWLIST with "
                "its reason (README: Static analysis)")


# ===========================================================================
# Rule 7 (tests): sleep-poll
# ===========================================================================

_DEADLINE_NAME_RE = re.compile(
    r"deadline|timeout|until|expires|t_end|end_t", re.IGNORECASE)


def _while_is_deadlined(node: ast.While) -> bool:
    """True if the loop condition references a deadline/monotonic guard."""
    for n in ast.walk(node.test):
        d = dotted_name(n)
        if d is None:
            continue
        if d in ("time.monotonic", "time.perf_counter", "time.time"):
            return True
        if _DEADLINE_NAME_RE.search(d.rsplit(".", 1)[-1]):
            return True
    return False


def scan_sleep_polls(tree: ast.AST, rel: str) -> "list[Finding]":
    """While-loops that time.sleep-poll without a deadline in their
    condition (shared with conftest's collection-time guard)."""
    findings: "list[Finding]" = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.While) or _while_is_deadlined(node):
            continue
        for sub in _iter_same_scope(node):
            if isinstance(sub, ast.Call) and dotted_name(sub.func) in (
                    "time.sleep", "sleep"):
                findings.append(Finding(
                    "sleep-poll", rel, sub.lineno,
                    "time.sleep polling loop with no deadline in its "
                    "condition — a stuck predicate hangs the suite "
                    "(flaky-soak trap); use the wait_until fixture from "
                    "conftest, or bound the loop on time.monotonic()"))
                break
    return findings


class SleepPollRule(Rule):
    name = "sleep-poll"
    description = (
        "test while-loops that poll with time.sleep must carry a "
        "deadline (use conftest's wait_until)"
    )
    scope = "tests"

    def check(self, f: SourceFile) -> "Iterable[Finding]":
        return scan_sleep_polls(f.tree, f.rel)


ALL_RULES = (
    LockDisciplineRule,
    DonationSafetyRule,
    BlockingInHotLoopRule,
    MetricDriftRule,
    FaultCoverageRule,
    EnvPinRule,
    SleepPollRule,
)
