"""Framework for sparkdl-lint: file model, rule protocol, runner, reports.

Design constraints (ISSUE 11):

* **Zero dependencies.** stdlib ``ast``/``re``/``json`` only — the linter
  gates run-tests.sh before anything heavy imports, and conftest reuses
  its scanners at collection time.
* **Line-scoped suppressions with required justification.**
  ``# sparkdl-lint: disable=rule-a,rule-b -- why this is safe`` on the
  flagged line (or alone on the line directly above). A suppression with
  no ``--`` justification is itself a finding
  (``suppression-missing-justification``), so "disabled because it was
  noisy" can never land silently.
* **Two-phase rules.** :meth:`Rule.check` sees one file at a time;
  :meth:`Rule.finalize` sees the whole :class:`Project` — the
  cross-file rules (metric drift, fault-site coverage, lock-order
  cycles) accumulate in ``check`` and report in ``finalize``.
* **Exit-code contract.** 0 clean, 1 active findings, 2 usage/internal
  error — what run-tests.sh keys its tier-1 gate on.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
import time
from typing import Any, Iterable, Iterator

__all__ = [
    "Finding",
    "LintReport",
    "Project",
    "Rule",
    "SourceFile",
    "dotted_name",
    "lint_paths",
    "str_const",
]

#: Comment grammar. The justification is everything after ``--``.
SUPPRESS_RE = re.compile(
    r"#\s*sparkdl-lint:\s*disable=([A-Za-z0-9_-]+(?:\s*,\s*[A-Za-z0-9_-]+)*)"
    r"(?:\s+--\s*(\S.*?))?\s*$"
)

#: Directory names never scanned (fixture corpora hold deliberate
#: violations for the linter's own tests; __pycache__ holds bytecode).
EXCLUDED_DIRS = ("__pycache__", "lint_fixtures")


# ---------------------------------------------------------------------------
# AST helpers shared by every rule
# ---------------------------------------------------------------------------


def dotted_name(node: ast.AST) -> "str | None":
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def str_const(node: ast.AST) -> "str | None":
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


# ---------------------------------------------------------------------------
# File model
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class Finding:
    """One rule violation at one source location."""

    rule: str
    path: str
    line: int
    message: str
    suppressed: bool = False
    justification: "str | None" = None

    def as_dict(self) -> dict:
        out: dict[str, Any] = {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "message": self.message,
        }
        if self.suppressed:
            out["suppressed"] = True
            out["justification"] = self.justification
        return out

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


class SourceFile:
    """One parsed Python file plus its suppression map."""

    def __init__(self, path: str, text: str, rel: "str | None" = None):
        self.path = path
        self.rel = rel if rel is not None else path
        self.text = text
        self.lines = text.splitlines()
        self.tree: "ast.AST | None" = None
        self.parse_error: "str | None" = None
        try:
            self.tree = ast.parse(text, filename=path)
        except SyntaxError as e:  # surfaced as a finding by the runner
            self.parse_error = f"{e.msg} (line {e.lineno})"
        #: line -> {rule: justification-or-None}. A suppression comment
        #: alone on a line also covers the NEXT line (the flagged
        #: statement's first line).
        self.suppressions: dict[int, dict[str, "str | None"]] = {}
        #: (line, rules) of suppressions lacking justification text
        self.bad_suppressions: list[tuple[int, str]] = []
        self._scan_suppressions()

    def _comment_lines(self) -> "Iterator[tuple[int, str]]":
        """(line, comment-text) for every REAL comment token — the
        suppression grammar must never match '# sparkdl-lint: ...'
        examples inside docstrings or string literals (a doc example
        without '--' would fail the gate; one inside a log string would
        silently suppress)."""
        import io
        import tokenize

        try:
            for tok in tokenize.generate_tokens(
                    io.StringIO(self.text).readline):
                if tok.type == tokenize.COMMENT:
                    yield tok.start[0], tok.string
        except (tokenize.TokenError, IndentationError, SyntaxError):
            # unparseable file: the runner already reports parse-error;
            # no suppressions is the safe default
            return

    def _scan_suppressions(self) -> None:
        spans = self._simple_stmt_spans()
        for i, comment in self._comment_lines():
            line = self.lines[i - 1]
            m = SUPPRESS_RE.search(comment)
            if m is None:
                continue
            rules = [r.strip() for r in m.group(1).split(",")]
            justification = m.group(2)
            if justification is None:
                self.bad_suppressions.append((i, m.group(1)))
            targets = {i}
            if line.lstrip().startswith("#"):
                targets.add(i + 1)  # standalone comment covers below
            # a target that OPENS a multi-line simple statement covers
            # the whole statement — findings anchor to the line of the
            # offending expression, which black-style wrapping may have
            # pushed onto a continuation line
            for t in list(targets):
                end = spans.get(t)
                if end is not None:
                    targets.update(range(t, end + 1))
            for t in targets:
                slot = self.suppressions.setdefault(t, {})
                for r in rules:
                    slot[r] = justification

    def _simple_stmt_spans(self) -> "dict[int, int]":
        """first line -> last line of every multi-line SIMPLE statement.
        Compound statements (if/for/with/def...) are excluded on
        purpose: a suppression above a loop must not blanket its whole
        body."""
        spans: "dict[int, int]" = {}
        if self.tree is None:
            return spans
        compound = (ast.If, ast.For, ast.AsyncFor, ast.While, ast.With,
                    ast.AsyncWith, ast.Try, ast.FunctionDef,
                    ast.AsyncFunctionDef, ast.ClassDef, ast.Match)
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.stmt) or isinstance(
                    node, compound):
                continue
            end = getattr(node, "end_lineno", None)
            if end is not None and end > node.lineno:
                prev = spans.get(node.lineno)
                spans[node.lineno] = max(prev or 0, end)
        return spans

    def suppression_for(self, rule: str, line: int) -> "tuple[bool, str | None]":
        slot = self.suppressions.get(line)
        if slot and rule in slot:
            return True, slot[rule]
        return False, None

    # -- classification used by rule scopes ---------------------------------
    @property
    def is_test(self) -> bool:
        parts = self.rel.replace(os.sep, "/").split("/")
        return ("tests" in parts
                or os.path.basename(self.rel).startswith("test_")
                or os.path.basename(self.rel) == "conftest.py")


class Project:
    """Everything one lint run sees: parsed files + auxiliary texts."""

    def __init__(self, files: "list[SourceFile]",
                 aux: "dict[str, tuple[str, str]]",
                 docs_text: str = ""):
        self.files = files
        #: name -> (path, text): non-Python inputs rules regex-scan
        #: (run-tests.sh fault plans live here)
        self.aux = aux
        #: concatenated README.md + PERF.md (metric-doc coverage source)
        self.docs_text = docs_text


# ---------------------------------------------------------------------------
# Rule protocol
# ---------------------------------------------------------------------------


class Rule:
    """Base class: subclasses set ``name``/``description`` and override
    :meth:`check` (per-file) and/or :meth:`finalize` (whole-project)."""

    name: str = ""
    description: str = ""
    #: which files check() sees: "production" (sparkdl_tpu, benches,
    #: tools — everything that is not a test), "tests", or "all"
    scope: str = "production"

    def wants(self, f: SourceFile) -> bool:
        if self.scope == "all":
            return True
        return f.is_test == (self.scope == "tests")

    def check(self, f: SourceFile) -> "Iterable[Finding]":
        return ()

    def finalize(self, project: Project) -> "Iterable[Finding]":
        return ()


# ---------------------------------------------------------------------------
# Runner
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class LintReport:
    """Outcome of one run: active findings gate, suppressed ones audit."""

    findings: "list[Finding]"
    suppressed: "list[Finding]"
    files_scanned: int
    rules: "list[str]"
    elapsed_s: float

    @property
    def exit_code(self) -> int:
        return 1 if self.findings else 0

    def as_dict(self) -> dict:
        return {
            "version": 1,
            "files_scanned": self.files_scanned,
            "rules": self.rules,
            "findings_total": len(self.findings),
            "suppressed_total": len(self.suppressed),
            "elapsed_s": round(self.elapsed_s, 3),
            "findings": [f.as_dict() for f in self.findings],
            "suppressed": [f.as_dict() for f in self.suppressed],
        }

    def to_json(self) -> str:
        return json.dumps(self.as_dict(), indent=2, sort_keys=False)

    def render_text(self) -> str:
        lines = [f.render() for f in self.findings]
        lines.append(
            f"sparkdl-lint: {len(self.findings)} finding(s), "
            f"{len(self.suppressed)} suppressed, {self.files_scanned} "
            f"file(s) in {self.elapsed_s:.2f}s"
        )
        return "\n".join(lines)


def _walk_py(path: str) -> "Iterator[str]":
    for root, dirs, names in os.walk(path):
        dirs[:] = sorted(d for d in dirs if d not in EXCLUDED_DIRS)
        for name in sorted(names):
            if name.endswith(".py"):
                yield os.path.join(root, name)


def _load_docs(root: str) -> str:
    chunks = []
    for name in ("README.md", "PERF.md"):
        p = os.path.join(root, name)
        if os.path.isfile(p):
            with open(p, encoding="utf-8") as fh:
                chunks.append(fh.read())
    return "\n".join(chunks)


def collect_project(paths: "Iterable[str]",
                    root: "str | None" = None) -> Project:
    """Build a :class:`Project` from files/dirs. ``.py`` paths parse;
    anything else (and an auto-discovered ``run-tests.sh`` next to
    ``root``) becomes an aux text. ``root`` (default: cwd) anchors
    README/PERF doc loading and relative display paths."""
    root = os.path.abspath(root if root is not None else os.getcwd())
    files: "list[SourceFile]" = []
    aux: "dict[str, tuple[str, str]]" = {}
    seen: set[str] = set()

    def rel(p: str) -> str:
        ap = os.path.abspath(p)
        if ap.startswith(root + os.sep):
            return os.path.relpath(ap, root)
        return p

    def add_py(p: str) -> None:
        ap = os.path.abspath(p)
        if ap in seen:
            return
        seen.add(ap)
        with open(ap, encoding="utf-8") as fh:
            files.append(SourceFile(ap, fh.read(), rel=rel(p)))

    for path in paths:
        if os.path.isdir(path):
            for p in _walk_py(path):
                add_py(p)
        elif path.endswith(".py"):
            add_py(path)
        else:
            with open(path, encoding="utf-8") as fh:
                aux[os.path.basename(path)] = (rel(path), fh.read())
    rt = os.path.join(root, "run-tests.sh")
    if "run-tests.sh" not in aux and os.path.isfile(rt):
        with open(rt, encoding="utf-8") as fh:
            aux["run-tests.sh"] = (rel(rt), fh.read())
    return Project(files, aux, docs_text=_load_docs(root))


def lint_paths(paths: "Iterable[str]", *,
               rules: "list[Rule] | None" = None,
               root: "str | None" = None) -> LintReport:
    """Run ``rules`` (default: every registered rule) over ``paths``."""
    if rules is None:
        from sparkdl_tpu.lint.rules import ALL_RULES

        rules = [cls() for cls in ALL_RULES]
    t0 = time.perf_counter()
    project = collect_project(paths, root=root)
    raw: "list[Finding]" = []
    for f in project.files:
        if f.parse_error is not None:
            raw.append(Finding("parse-error", f.rel, 1,
                               f"cannot parse: {f.parse_error}"))
            continue
        for line, rules_txt in f.bad_suppressions:
            raw.append(Finding(
                "suppression-missing-justification", f.rel, line,
                f"suppression of [{rules_txt}] carries no justification — "
                "append ' -- <why this is safe>'"))
        for rule in rules:
            if rule.wants(f):
                raw.extend(rule.check(f))
    for rule in rules:
        raw.extend(rule.finalize(project))

    by_rel = {f.rel: f for f in project.files}
    active: "list[Finding]" = []
    suppressed: "list[Finding]" = []
    for finding in raw:
        src = by_rel.get(finding.path)
        if src is not None and finding.rule != \
                "suppression-missing-justification":
            hit, justification = src.suppression_for(
                finding.rule, finding.line)
            if hit:
                finding.suppressed = True
                finding.justification = justification
                suppressed.append(finding)
                continue
        active.append(finding)
    key = (lambda f: (f.path, f.line, f.rule, f.message))
    active.sort(key=key)
    suppressed.sort(key=key)
    return LintReport(
        findings=active,
        suppressed=suppressed,
        files_scanned=len(project.files),
        rules=[r.name for r in rules],
        elapsed_s=time.perf_counter() - t0,
    )
