"""CLI: ``python -m sparkdl_tpu.lint [paths...]``.

Exit codes: 0 clean, 1 findings, 2 usage/internal error — the contract
run-tests.sh's tier-1 lint stage keys on.
"""

from __future__ import annotations

import argparse
import json
import sys

from sparkdl_tpu.lint.core import lint_paths
from sparkdl_tpu.lint.rules import ALL_RULES


def main(argv: "list[str] | None" = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m sparkdl_tpu.lint",
        description="sparkdl-lint: AST invariant checker for concurrency, "
                    "donation, and contract drift (README: Static "
                    "analysis)",
    )
    parser.add_argument(
        "paths", nargs="*", default=["sparkdl_tpu", "tests"],
        help="files/dirs to lint (.py parsed; other files become aux "
             "texts for the fault-plan scanner). Default: sparkdl_tpu "
             "tests")
    parser.add_argument(
        "--format", choices=("text", "json"), default="text",
        help="stdout format (default text)")
    parser.add_argument(
        "--output", metavar="PATH",
        help="also write the full JSON report here")
    parser.add_argument(
        "--rule", action="append", metavar="NAME",
        help="run only the named rule(s); repeatable")
    parser.add_argument(
        "--root", default=None,
        help="repo root for README/PERF/run-tests.sh discovery and "
             "relative paths (default: cwd)")
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print the rule catalog and exit")
    args = parser.parse_args(argv)

    if args.list_rules:
        for cls in ALL_RULES:
            print(f"{cls.name:22s} {cls.description}")
        return 0

    rules = None
    if args.rule:
        by_name = {cls.name: cls for cls in ALL_RULES}
        unknown = [r for r in args.rule if r not in by_name]
        if unknown:
            print(f"unknown rule(s): {', '.join(unknown)} "
                  f"(see --list-rules)", file=sys.stderr)
            return 2
        rules = [by_name[r]() for r in args.rule]

    try:
        report = lint_paths(args.paths, rules=rules, root=args.root)
    except OSError as e:
        print(f"sparkdl-lint: {e}", file=sys.stderr)
        return 2

    if args.output:
        with open(args.output, "w", encoding="utf-8") as fh:
            fh.write(report.to_json() + "\n")
    if args.format == "json":
        print(report.to_json())
    else:
        print(report.render_text())
    return report.exit_code


if __name__ == "__main__":
    sys.exit(main())
