"""sparkdl-lint: AST-based invariant checker for this codebase (ISSUE 11).

Five subsystems now rest on conventions no compiler enforces: lock-guarded
mutable state in the serving/reliability threads, donated JAX buffers that
must never be read after dispatch, the ``sparkdl_*`` metric families, the
``fault_point`` site names, and the ``resolve_pin`` env-var contract. This
package machine-checks them — the graph-layer validation discipline of the
TensorFlow/tf.data systems papers (PAPERS.md), applied to the host-side
Python that orchestrates the chips — so later PRs can refactor freely
without re-deriving the invariants by review.

Zero-dependency by construction: stdlib ``ast`` + ``re`` only, importable
before jax exists (conftest and run-tests.sh run it as a tier-1 gate).

Usage::

    python -m sparkdl_tpu.lint sparkdl_tpu/ tests/           # human output
    python -m sparkdl_tpu.lint --format json sparkdl_tpu/    # machine output
    python -m sparkdl_tpu.lint --list-rules

Exit codes: 0 = clean, 1 = findings, 2 = usage/internal error.

Suppressions are line-scoped comments with REQUIRED justification text::

    x = 1  # sparkdl-lint: disable=lock-discipline -- published before start()

(on the flagged line, or alone on the line above it). A suppression
without ``-- <why>`` is itself a finding. See README "Static analysis".
"""

from sparkdl_tpu.lint.core import (
    Finding,
    LintReport,
    Project,
    Rule,
    SourceFile,
    lint_paths,
)
from sparkdl_tpu.lint.rules import ALL_RULES

__all__ = [
    "ALL_RULES",
    "Finding",
    "LintReport",
    "Project",
    "Rule",
    "SourceFile",
    "lint_paths",
]
