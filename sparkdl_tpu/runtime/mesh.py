"""Device-mesh discovery and construction.

The reference has no device mesh: its inference parallelism is one TF
session per Spark executor and its training parallelism is a Horovod ring
(SURVEY.md 2.11/2.13). The TPU-native equivalent is a named
``jax.sharding.Mesh`` over which pjit/shard_map place collectives on ICI.
This module owns mesh axis conventions for the whole framework:

  axis name | meaning
  ----------+----------------------------------------------
  ``dp``    | data parallel (batch split; psum of grads)
  ``fsdp``  | fully-sharded data parallel (param shard over dp peers)
  ``tp``    | tensor parallel (weight-column/row split)
  ``sp``    | sequence/context parallel (ring attention)
  ``pp``    | pipeline parallel (stage split)
  ``ep``    | expert parallel (MoE expert split)

Every model/transform in the framework refers to these names, never to raw
device indices.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

#: Canonical axis ordering. dp outermost (DCN-friendly), then pp, fsdp, sp,
#: tp/ep innermost (highest-bandwidth ICI neighbours).
AXIS_ORDER = ("dp", "pp", "fsdp", "sp", "tp", "ep")


class MeshShapeError(ValueError):
    """A requested parallelism layout cannot be laid over the available
    devices (non-divisor axis sizes, duplicate axis names, bad product).

    Raised at mesh-construction time with the device count in the message
    — the alternative is an opaque reshape/jit error long after the bad
    shape was chosen (partition/mesh_factory.py is the loud front door)."""


def resolve_axis_sizes(sizes: "dict[str, int]", n_devices: int) -> dict[str, int]:
    """Resolve an ordered ``{axis: size}`` layout against ``n_devices``:
    at most one ``-1`` axis is inferred, everything else validated with a
    typed :class:`MeshShapeError` naming the device count. The one
    implementation behind :meth:`MeshSpec.resolve` and
    ``partition.mesh_factory``'s custom-axes builder."""
    sizes = dict(sizes)
    unknown = [a for a, s in sizes.items() if s == -1]
    if len(unknown) > 1:
        raise MeshShapeError(
            f"more than one -1 axis to infer: {unknown}"
        )
    bad = {a: s for a, s in sizes.items() if s != -1 and s < 1}
    if bad:
        raise MeshShapeError(
            f"mesh axis sizes must be >= 1 (or one -1 to infer), got "
            f"{bad} over {n_devices} devices"
        )
    known = math.prod(s for s in sizes.values() if s != -1)
    if unknown:
        if n_devices % known != 0:
            raise MeshShapeError(
                f"{n_devices} devices not divisible by the fixed axes "
                f"product {known} "
                f"({ {a: s for a, s in sizes.items() if s not in (1, -1)} })"
            )
        sizes[unknown[0]] = n_devices // known
    elif known != n_devices:
        raise MeshShapeError(
            f"mesh axes product {known} "
            f"({ {a: s for a, s in sizes.items() if s != 1} }) != "
            f"device count {n_devices}"
        )
    return sizes


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Logical parallelism layout, independent of physical device count.

    A size of 1 means the axis is inert (present in the mesh so that
    PartitionSpecs mentioning it always resolve, but no actual splitting).
    Sizes of -1 (at most one) are inferred from the device count.
    """

    dp: int = -1
    pp: int = 1
    fsdp: int = 1
    sp: int = 1
    tp: int = 1
    ep: int = 1

    def sizes(self) -> dict[str, int]:
        return {a: getattr(self, a) for a in AXIS_ORDER}

    def resolve(self, n_devices: int) -> dict[str, int]:
        """Fill in the single -1 axis from n_devices; validate the product."""
        return resolve_axis_sizes(self.sizes(), n_devices)

    def build(self, devices: Sequence[jax.Device] | None = None) -> Mesh:
        if devices is None:
            devices = jax.devices()
        sizes = self.resolve(len(devices))
        shape = tuple(sizes[a] for a in AXIS_ORDER)
        arr = np.asarray(devices, dtype=object).reshape(shape)
        return Mesh(arr, AXIS_ORDER)


def data_parallel_mesh(devices: Sequence[jax.Device] | None = None) -> Mesh:
    """All devices on the ``dp`` axis — the reference-parity layout

    (its only parallelism is DP; SURVEY.md 2.11)."""
    return MeshSpec(dp=-1).build(devices)


def single_device_mesh(device: jax.Device | None = None) -> Mesh:
    if device is None:
        device = jax.devices()[0]
    return MeshSpec(dp=1).build([device])


def batch_sharding(mesh: Mesh, batch_axes: Sequence[str] = ("dp", "fsdp")) -> NamedSharding:
    """Sharding that splits the leading (batch) dim over the data axes."""
    return NamedSharding(mesh, P(tuple(batch_axes)))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def mesh_axis_size(mesh: Mesh, axis: str) -> int:
    return mesh.shape[axis]


def mesh_context(mesh: Mesh):
    """``with mesh_context(mesh):`` across jax versions.

    jax >= 0.5 spells the ambient-mesh scope ``jax.set_mesh(mesh)``; on
    0.4.x the Mesh object itself is the context manager that installs the
    thread-local physical mesh (which ``with_sharding_constraint`` and
    ``parallel.tensor_parallel.constrain_dim`` resolve axis names
    against). One call site, either runtime.
    """
    set_mesh = getattr(jax, "set_mesh", None)
    if set_mesh is not None:
        return set_mesh(mesh)
    return mesh
