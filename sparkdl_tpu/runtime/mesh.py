"""Device-mesh discovery and construction.

The reference has no device mesh: its inference parallelism is one TF
session per Spark executor and its training parallelism is a Horovod ring
(SURVEY.md 2.11/2.13). The TPU-native equivalent is a named
``jax.sharding.Mesh`` over which pjit/shard_map place collectives on ICI.
This module owns mesh axis conventions for the whole framework:

  axis name | meaning
  ----------+----------------------------------------------
  ``dp``    | data parallel (batch split; psum of grads)
  ``fsdp``  | fully-sharded data parallel (param shard over dp peers)
  ``tp``    | tensor parallel (weight-column/row split)
  ``sp``    | sequence/context parallel (ring attention)
  ``pp``    | pipeline parallel (stage split)
  ``ep``    | expert parallel (MoE expert split)

Every model/transform in the framework refers to these names, never to raw
device indices.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

#: Canonical axis ordering. dp outermost (DCN-friendly), then pp, fsdp, sp,
#: tp/ep innermost (highest-bandwidth ICI neighbours).
AXIS_ORDER = ("dp", "pp", "fsdp", "sp", "tp", "ep")


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Logical parallelism layout, independent of physical device count.

    A size of 1 means the axis is inert (present in the mesh so that
    PartitionSpecs mentioning it always resolve, but no actual splitting).
    Sizes of -1 (at most one) are inferred from the device count.
    """

    dp: int = -1
    pp: int = 1
    fsdp: int = 1
    sp: int = 1
    tp: int = 1
    ep: int = 1

    def sizes(self) -> dict[str, int]:
        return {a: getattr(self, a) for a in AXIS_ORDER}

    def resolve(self, n_devices: int) -> dict[str, int]:
        """Fill in the single -1 axis from n_devices; validate the product."""
        sizes = self.sizes()
        unknown = [a for a, s in sizes.items() if s == -1]
        if len(unknown) > 1:
            raise ValueError(f"MeshSpec has more than one -1 axis: {unknown}")
        known = math.prod(s for s in sizes.values() if s != -1)
        if unknown:
            if n_devices % known != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes product {known}"
                )
            sizes[unknown[0]] = n_devices // known
        elif known != n_devices:
            raise ValueError(
                f"MeshSpec product {known} != device count {n_devices}"
            )
        return sizes

    def build(self, devices: Sequence[jax.Device] | None = None) -> Mesh:
        if devices is None:
            devices = jax.devices()
        sizes = self.resolve(len(devices))
        shape = tuple(sizes[a] for a in AXIS_ORDER)
        arr = np.asarray(devices, dtype=object).reshape(shape)
        return Mesh(arr, AXIS_ORDER)


def data_parallel_mesh(devices: Sequence[jax.Device] | None = None) -> Mesh:
    """All devices on the ``dp`` axis — the reference-parity layout

    (its only parallelism is DP; SURVEY.md 2.11)."""
    return MeshSpec(dp=-1).build(devices)


def single_device_mesh(device: jax.Device | None = None) -> Mesh:
    if device is None:
        device = jax.devices()[0]
    return MeshSpec(dp=1).build([device])


def batch_sharding(mesh: Mesh, batch_axes: Sequence[str] = ("dp", "fsdp")) -> NamedSharding:
    """Sharding that splits the leading (batch) dim over the data axes."""
    return NamedSharding(mesh, P(tuple(batch_axes)))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def mesh_axis_size(mesh: Mesh, axis: str) -> int:
    return mesh.shape[axis]


def mesh_context(mesh: Mesh):
    """``with mesh_context(mesh):`` across jax versions.

    jax >= 0.5 spells the ambient-mesh scope ``jax.set_mesh(mesh)``; on
    0.4.x the Mesh object itself is the context manager that installs the
    thread-local physical mesh (which ``with_sharding_constraint`` and
    ``parallel.tensor_parallel.constrain_dim`` resolve axis names
    against). One call site, either runtime.
    """
    set_mesh = getattr(jax, "set_mesh", None)
    if set_mesh is not None:
        return set_mesh(mesh)
    return mesh
