"""Dtype policy for TPU execution.

The reference runs TF graphs at whatever dtype the frozen graph was built
with (float32 everywhere; see SURVEY.md 2.15/2.18). On TPU the MXU natively
multiplies bfloat16 with float32 accumulation, so the idiomatic policy is
float32 parameters / bfloat16 compute / float32 outputs. This module is the
single switch for that choice.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class DtypePolicy:
    """Dtype policy applied by models and transformers.

    Attributes:
      param_dtype: dtype parameters are stored in (master copy).
      compute_dtype: dtype activations/matmuls run in.
      output_dtype: dtype returned to the caller (DataFrame columns).
    """

    param_dtype: jnp.dtype = jnp.float32
    compute_dtype: jnp.dtype = jnp.bfloat16
    output_dtype: jnp.dtype = jnp.float32

    def cast_inputs(self, x):
        return jax.tree_util.tree_map(
            lambda a: a.astype(self.compute_dtype)
            if jnp.issubdtype(a.dtype, jnp.floating)
            else a,
            x,
        )

    def cast_outputs(self, x):
        return jax.tree_util.tree_map(
            lambda a: a.astype(self.output_dtype)
            if jnp.issubdtype(a.dtype, jnp.floating)
            else a,
            x,
        )


def default_policy(platform: str | None = None) -> DtypePolicy:
    """bfloat16 compute on TPU, float32 elsewhere (CPU tests stay exact)."""
    if platform is None:
        platform = jax.default_backend()
    if platform in ("tpu", "axon"):
        return DtypePolicy()
    return DtypePolicy(compute_dtype=jnp.float32)


#: Policy that disables mixed precision entirely (used by oracle tests).
FLOAT32 = DtypePolicy(compute_dtype=jnp.float32)
