"""Bucketed batching: static shapes for XLA from ragged DataFrame partitions.

Spark partitions are ragged; the reference simply runs ``Session.run`` on
whatever block size TensorFrames hands it (SURVEY.md 3.1), which is fine for
TF's dynamic shapes but would trigger one XLA recompile per distinct batch
size on TPU. We instead pad every batch up to a small set of bucket sizes so
each jitted executable is compiled at most once per bucket.
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, Iterator, Sequence

import numpy as np

_METRICS = None


def _note_batch(n_valid: int, bucket: int) -> None:
    """Registry spine: rows vs pad rows per assembled batch, and whether
    the batch hit its bucket exactly (pad waste is what bucket tuning
    buys back). Lazy handles keep this module import-light."""
    global _METRICS
    if _METRICS is None:
        from sparkdl_tpu.observability.registry import registry

        _METRICS = (
            registry().counter(
                "sparkdl_batch_rows_total", "live rows through batching"),
            registry().counter(
                "sparkdl_batch_pad_rows_total",
                "pad rows dispatched (wasted device work)"),
            registry().counter(
                "sparkdl_batch_bucket_dispatch_total",
                "assembled batches by bucket fit", labels=("fit",)),
        )
    rows, pad, fit = _METRICS
    if n_valid:
        rows.inc(n_valid)
    if bucket > n_valid:
        pad.inc(bucket - n_valid)
    fit.inc(fit="exact" if bucket == n_valid else "padded")


def default_buckets(max_batch: int, min_bucket: int = 8) -> tuple[int, ...]:
    """Powers of two from min_bucket up to max_batch (inclusive)."""
    buckets = []
    b = min_bucket
    while b < max_batch:
        buckets.append(b)
        b *= 2
    buckets.append(max_batch)
    return tuple(buckets)


@dataclasses.dataclass(frozen=True)
class PaddedBatch:
    """A batch padded up to a bucket size.

    ``arrays`` leading dims equal ``bucket``; rows ``[n_valid:]`` are padding
    (repeats of row 0 so they are numerically harmless; zeros when the batch
    is empty) and must be dropped from the output.
    """

    arrays: dict[str, np.ndarray]
    n_valid: int
    bucket: int

    def unpad(self, out: np.ndarray) -> np.ndarray:
        return out[: self.n_valid]


def pow2_bucket(n: int, lo: int = 8, hi: "int | None" = None) -> int:
    """Smallest power-of-two-from-``lo`` bucket covering ``n``, capped at
    ``hi`` — the one compile-reuse bucketing policy (prompt buckets,
    prefill-chunk widths, block-table gather depths) so every jit cache
    lines up on the same shapes."""
    b = lo
    while b < n:
        b *= 2
    return b if hi is None else min(b, hi)


def pick_bucket(n: int, buckets: Sequence[int]) -> int:
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(f"batch of {n} rows exceeds largest bucket {buckets[-1]}")


def pad_to_bucket(arrays: dict[str, np.ndarray], buckets: Sequence[int]) -> PaddedBatch:
    n = next(iter(arrays.values())).shape[0]
    if n == 0:
        # Serving flush ticks can legitimately fire with zero queued rows:
        # pad with zeros (there is no row 0 to repeat) up to the smallest
        # bucket, n_valid=0 so unpad() drops everything.
        bucket = min(buckets)
        _note_batch(0, bucket)
        return PaddedBatch(
            {k: np.zeros((bucket,) + a.shape[1:], a.dtype)
             for k, a in arrays.items()},
            0, bucket,
        )
    bucket = pick_bucket(n, buckets)
    _note_batch(n, bucket)
    if bucket == n:
        return PaddedBatch(arrays, n, bucket)
    return PaddedBatch(
        {k: _repeat_pad(a, bucket) for k, a in arrays.items()}, n, bucket
    )


def _repeat_pad(a: np.ndarray, bucket: int) -> np.ndarray:
    """Pad rows [n:bucket] with copies of row 0 (the PaddedBatch contract —
    the single place the padding convention lives)."""
    n = a.shape[0]
    if bucket == n:
        return a
    return np.concatenate([a, np.repeat(a[:1], bucket - n, axis=0)], axis=0)


def rebatch(
    rows: Iterable[dict[str, np.ndarray]],
    batch_size: int,
    buckets: Sequence[int] | None = None,
) -> Iterator[PaddedBatch]:
    """Group per-row dicts into padded batches of at most ``batch_size``.

    Full batches come out at exactly ``batch_size`` (one compile); the ragged
    tail is padded up to the nearest bucket.
    """
    if buckets is None:
        buckets = default_buckets(batch_size)
    pending: list[dict[str, np.ndarray]] = []
    for row in rows:
        pending.append(row)
        if len(pending) == batch_size:
            yield _stack(pending, buckets)
            pending = []
    if pending:
        yield _stack(pending, buckets)


#: below this many bytes per assembled tensor, plain np.stack wins (thread
#: spawn overhead exceeds the memcpy fan-out gain)
_NATIVE_PACK_MIN_BYTES = 1 << 20


def _stack(rows: list[dict[str, np.ndarray]], buckets: Sequence[int]) -> PaddedBatch:
    keys = rows[0].keys()
    n = len(rows)
    bucket = pick_bucket(n, buckets)
    _note_batch(n, bucket)
    arrays = {k: _assemble([np.asarray(r[k]) for r in rows], bucket)
              for k in keys}
    return PaddedBatch(arrays, n, bucket)


def _assemble(vals: list[np.ndarray], bucket: int) -> np.ndarray:
    """Stack + pad rows to [bucket, ...]; large batches go through the
    native threaded packer (sparkdl_tpu.native), small ones through numpy."""
    v0 = vals[0]
    if (v0.nbytes * bucket >= _NATIVE_PACK_MIN_BYTES
            and all(v.shape == v0.shape and v.dtype == v0.dtype for v in vals)):
        from sparkdl_tpu.native import bridge

        if bridge.native_available():
            packed = bridge.pack_rows(vals, bucket=bucket, row_stride=v0.nbytes)
            return packed.view(v0.dtype).reshape((bucket,) + v0.shape)
    return _repeat_pad(np.stack(vals, axis=0), bucket)


def pad_batch_to_multiple(arrays: dict[str, np.ndarray], multiple: int) -> PaddedBatch:
    """Pad so the leading dim divides ``multiple`` (for sharded batch dims)."""
    n = next(iter(arrays.values())).shape[0]
    bucket = ((n + multiple - 1) // multiple) * multiple
    return pad_to_bucket(arrays, [bucket])
