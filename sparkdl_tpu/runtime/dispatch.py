"""Fused multi-step dispatch: the bench.py scan-K win as a runtime layer.

PERF.md's profiling established that ~2.4 ms of every device dispatch on
the relayed chip is relay/launch overhead, and that chaining K
device-resident steps inside one jit (``lax.scan``) is the single
largest measured lever on the north-star bench (7,868 -> 9,766 img/s).
This module makes that amortization generic so every production hot path
— :class:`~sparkdl_tpu.transformers._inference.BatchedRunner` batches,
``train/finetune`` optimizer steps, ``serving/continuous`` decode tokens
— pays one dispatch per K steps instead of one per step. Same
pipeline-overhead argument tf.data makes for input pipelines (Murray et
al., arXiv:2101.12127) and deferred graphs make for TensorFlow (Abadi et
al., arXiv:1605.08695), applied at the dispatch boundary.

Three pieces:

* :func:`calibrate_dispatch_gap` — measured per-dispatch overhead of
  THIS process's backend (a trivial jitted program timed wall-to-wall:
  anything it "takes" is launch/relay cost, not compute — the PERF.md
  measurement-discipline probe, productionized);
* :class:`ChainPolicy` — picks K from the measured program time vs the
  calibrated gap so the overhead share stays under ``target_overhead``,
  degrading to K=1 for long programs (>~50 ms, where chaining buys
  nothing and only delays host visibility);
* :class:`ScanChainer` — stacks K same-shape device-resident inputs,
  runs one jit-compiled ``lax.scan`` over them, and unstacks the
  results. An iteration counter is threaded through the carry so the
  loop body stays iteration-dependent and CSE/loop-invariant motion can
  never collapse the K steps into one. :func:`chain_carry` is the
  carried-state (training) variant with buffer donation.

Everything dispatched through here lands in the observability spine:
``sparkdl_dispatches_total{path=...}``, the
``sparkdl_dispatch_chain_len`` histogram, the per-dispatch wall
histogram ``sparkdl_dispatch_seconds``, and a ``dispatch.chain`` span —
so the dispatch-gap share is a first-class metric in every bench JSON
artifact (:func:`overhead_share`).
"""

from __future__ import annotations

import dataclasses
import math
import os
import time
from typing import Any, Callable, Iterable, Iterator

from sparkdl_tpu.observability.registry import registry
from sparkdl_tpu.observability.tracing import span
from sparkdl_tpu.reliability.faults import fault_point

__all__ = [
    "ChainPolicy",
    "ScanChainer",
    "SpecPolicy",
    "calibrate_dispatch_gap",
    "chain_carry",
    "default_chain_k",
    "dispatch_metrics",
    "overhead_share",
    "record_dispatch",
    "shape_key",
]

#: Chain-length histogram bounds: powers of two up to the largest K the
#: bench ever measured a win at (PERF.md: saturation by K=32..64).
CHAIN_LEN_BUCKETS: tuple[float, ...] = (1, 2, 4, 8, 16, 32, 64, 128)

_METRICS = None


def dispatch_metrics():
    """Lazy handles for the dispatch spine (one tuple per process):
    (dispatches counter by path, chain-length histogram, wall histogram).
    """
    global _METRICS
    if _METRICS is None:
        _METRICS = (
            registry().counter(
                "sparkdl_dispatches_total",
                "device dispatches issued (one jitted call = one)",
                labels=("path",)),
            registry().histogram(
                "sparkdl_dispatch_chain_len",
                "steps fused into each device dispatch",
                labels=("path",), buckets=CHAIN_LEN_BUCKETS),
            registry().histogram(
                "sparkdl_dispatch_seconds",
                "wall time of each device dispatch (all chained steps)",
                labels=("path",)),
        )
    return _METRICS


def record_dispatch(path: str, k: int, wall_s: "float | None" = None) -> None:
    """Record one device dispatch that fused ``k`` steps on ``path``."""
    dispatches, chain_len, wall = dispatch_metrics()
    dispatches.inc(path=path)
    chain_len.observe(k, path=path)
    if wall_s is not None:
        wall.observe(wall_s, path=path)


def dispatch_count(path: "str | None" = None) -> float:
    """Current value of the dispatch counter (summed over paths when
    ``path`` is None) — the benches' ``dispatch_count`` source."""
    fam = registry().get("sparkdl_dispatches_total")
    if fam is None:
        return 0.0
    values = fam.snapshot_values()
    if path is not None:
        return float(values.get(f'path="{path}"', 0.0))
    return float(sum(values.values()))


# -- dispatch-gap calibration -------------------------------------------------

_GAP_CACHE: "dict[str, float]" = {}


def calibrate_dispatch_gap(samples: int = 30, *,
                           refresh: bool = False) -> float:
    """Median wall seconds of a trivial jitted dispatch on the current
    backend.

    A one-element elementwise program has effectively zero compute, so
    its wall time IS the per-dispatch overhead (launch + relay RTT
    share) — the PERF.md probe that measured ~2.4 ms on the relayed v5e
    and ~10 µs on local CPU. Cached per backend;
    ``SPARKDL_TPU_DISPATCH_GAP_MS`` overrides (no measurement run), for
    environments where a calibration burst is unwelcome.
    """
    env = os.environ.get("SPARKDL_TPU_DISPATCH_GAP_MS")
    if env:
        return float(env) / 1e3
    import jax

    backend = jax.default_backend()
    if not refresh and backend in _GAP_CACHE:
        return _GAP_CACHE[backend]
    import jax.numpy as jnp

    probe = jax.jit(lambda x: x + 1.0)
    x = jax.device_put(jnp.zeros((), jnp.float32))
    probe(x).block_until_ready()  # compile outside the timed region
    times = []
    for _ in range(max(3, samples)):
        t0 = time.perf_counter()
        probe(x).block_until_ready()
        times.append(time.perf_counter() - t0)
    times.sort()
    gap = times[len(times) // 2]
    _GAP_CACHE[backend] = gap
    registry().gauge(
        "sparkdl_dispatch_gap_seconds",
        "calibrated per-dispatch overhead of this backend",
    ).set(gap)
    return gap


def overhead_share(n_dispatches: float, wall_s: float,
                   gap_s: "float | None" = None) -> "float | None":
    """Dispatch-overhead share of a measured wall interval:
    ``n * gap / wall`` — what fraction of the wall clock was launch/relay
    cost rather than device program. The number the benches emit so the
    trajectory captures amortization, not just img/s."""
    if wall_s <= 0 or n_dispatches <= 0:
        return None
    if gap_s is None:
        gap_s = calibrate_dispatch_gap()
    return min(1.0, n_dispatches * gap_s / wall_s)


# -- chain-length policy ------------------------------------------------------


@dataclasses.dataclass
class ChainPolicy:
    """Pick K so the dispatch-gap share of wall time stays under target.

    Overhead share of a K-chain is ``gap / (gap + K * program)``; solving
    for share <= ``target_overhead`` gives
    ``K >= gap * (1 - t) / (t * program)``. K is rounded UP to a power of
    two (bounded jit-cache churn: at most log2(max_chain) compiles per
    program) and clamped to ``[1, max_chain]``. Programs longer than
    ``max_program_s`` (~50 ms) get K=1 — the gap is already <5% there,
    and chaining only delays host visibility (metrics, checkpoints,
    retirements).

    ``record(wall_s, k)`` feeds the measured per-step program time back
    (EMA); until the first record, :meth:`chain_len` returns 1 so the
    first dispatch doubles as the measurement probe.
    """

    target_overhead: float = 0.02
    max_chain: int = 32
    max_program_s: float = 0.050
    gap_s: "float | None" = None  # None: calibrate lazily on first use
    ema: float = 0.5
    program_s: "float | None" = dataclasses.field(default=None)

    def gap(self) -> float:
        if self.gap_s is None:
            self.gap_s = calibrate_dispatch_gap()
        return self.gap_s

    def record(self, wall_s: float, k: int) -> None:
        """Fold one measured dispatch (k fused steps, wall seconds).

        Deliberately does NOT trigger gap calibration: record() sits on
        every hot path even when the chain length is pinned (where the
        policy is only a program-time estimator, e.g. the decode
        deadline bound), and the 30-probe calibration burst must never
        ride a production dispatch. Until the gap is known the estimate
        includes it — a slight overestimate, which only makes
        chain_len()/deadline bounds more conservative.
        """
        gap = self.gap_s if self.gap_s is not None else 0.0
        prog = max((wall_s - gap) / max(k, 1), 1e-9)
        if self.program_s is None:
            self.program_s = prog
        else:
            self.program_s += self.ema * (prog - self.program_s)

    def chain_len(self) -> int:
        if self.program_s is None:
            return 1  # first dispatch measures
        if self.program_s >= self.max_program_s:
            return 1  # long program: overhead share already < target-ish
        t = self.target_overhead
        k = self.gap() * (1.0 - t) / (t * self.program_s)
        if k <= 1.0:
            return 1
        # the 1e-9 guard keeps float fuzz from bumping an exact power of
        # two (ideal K = 4.0000000001) to the next one
        return min(self.max_chain, 1 << math.ceil(math.log2(k) - 1e-9))


@dataclasses.dataclass
class SpecPolicy:
    """Pick the speculative verify width from measured acceptance.

    :class:`ChainPolicy` chains k IDENTICAL steps, so its only question
    is dispatch-gap amortization. A speculative verify chains k
    *conditional* steps: position j only produces a real token if every
    draft before it was accepted, so the useful width depends on the
    measured per-position acceptance rate ``p``. Expected real tokens
    from a width-k verify are ``E(k) = (1-p^k)/(1-p)`` (a geometric
    series — each extra position converts with one more factor of p).

    ``spec_len`` returns the largest power-of-two ``k <= max_k`` whose
    expected utilization stays above ``util`` (``E(k) >= util * k``):
    below that, the marginal verify positions are mostly wasted FLOPs.
    Acceptance below ``min_rate`` returns 1 — drafting is not paying
    for itself and the engine serves plain (chained) decode instead.

    The estimator is a pair of geometrically-decayed counts
    (proposed/accepted per dispatch), seeded with an OPTIMISTIC prior:
    cold engines open at full width (the first verifies double as
    measurement probes — repetitive/shared-prefix workloads, the ones
    speculation exists for, get their speedup immediately), and one
    unlucky one-draft dispatch cannot poison the estimate the way a
    plain EMA of per-dispatch ratios would. Stood-down is NOT
    terminal: every ``probe_every``-th consultation while below
    ``min_rate`` returns a width-2 probation probe — the same
    reintegration discipline as quarantined replicas — so a workload
    that turns acceptance-friendly again is re-detected without any
    operator action.
    """

    max_k: int = 8
    util: float = 0.5
    min_rate: float = 0.2
    decay: float = 0.2
    prior: float = 8.0
    probe_every: int = 16

    def __post_init__(self) -> None:
        self._proposed = self.prior
        self._accepted = self.prior
        self._stood_down = 0

    @property
    def rate(self) -> float:
        """Decayed-count acceptance estimate (optimistic at cold)."""
        return self._accepted / self._proposed

    def record(self, proposed: int, accepted: int) -> None:
        if proposed < 1:
            return
        self._proposed = (1 - self.decay) * self._proposed + proposed
        self._accepted = (1 - self.decay) * self._accepted + accepted

    def expected_tokens(self, k: int) -> float:
        """E(k) under the current acceptance estimate."""
        p = min(max(self.rate, 0.0), 0.999999)
        return (1.0 - p ** k) / (1.0 - p)

    def spec_len(self) -> int:
        if self.max_k < 2:
            return 1
        if self.rate < self.min_rate:
            self._stood_down += 1
            if self._stood_down % self.probe_every == 0:
                return 2  # probation probe: re-measure acceptance
            return 1
        self._stood_down = 0
        k = 2
        while (2 * k <= self.max_k
               and self.expected_tokens(2 * k) >= self.util * 2 * k):
            k *= 2
        return k


def default_chain_k() -> "int | None":
    """Process-wide chain_k override: ``SPARKDL_TPU_CHAIN_K`` (int), or
    None meaning auto (ChainPolicy decides from measurements). A value
    below 1 is a misconfiguration and raises — same contract as the
    constructor argument (``1`` is how chaining is disabled)."""
    env = os.environ.get("SPARKDL_TPU_CHAIN_K")
    if not env:
        return None
    k = int(env)
    if k < 1:
        raise ValueError(
            f"SPARKDL_TPU_CHAIN_K must be >= 1, got {env!r} "
            "(set 1 to disable chaining)"
        )
    return k


# -- the chainer --------------------------------------------------------------


def shape_key(tree: Any) -> Any:
    """Hashable (structure, shapes, dtypes) key for a batch pytree: only
    inputs with equal keys may join one chain (the scan stacks them).
    The single grouping predicate — ``ScanChainer.map_stream`` and the
    finetune chain loop both use it, so the semantics cannot drift."""
    import jax

    leaves, treedef = jax.tree.flatten(tree)
    return treedef, tuple(
        (tuple(getattr(l, "shape", ())), str(getattr(l, "dtype", type(l))))
        for l in leaves
    )


class ScanChainer:
    """Fuse K same-shape ``step_fn`` applications into one device dispatch.

    ``step_fn(x) -> y`` is any jittable map (no carried state; use
    :func:`chain_carry` for optimizer-style carries). A chained dispatch
    jit-compiles::

        def chained(*xs):
            stacked = tree.map(stack, *xs)      # free inside jit: fused
            def body(i, x):                     # i threads iteration
                return i + 1, step_fn(x)        # dependence (anti-CSE)
            _, ys = lax.scan(body, 0, stacked)
            return ys

    and unstacks ``ys`` back into per-step outputs — bitwise identical to
    K separate ``jit(step_fn)`` calls (the scan body is the same HLO;
    parity is pinned by tests/runtime/test_dispatch.py). jit's shape
    cache keys on (K, input shapes): one compile per (chain length,
    bucket).

    ``chain_k``: None = auto (``SPARKDL_TPU_CHAIN_K`` env if set, else
    the :class:`ChainPolicy` picks from measured program time vs the
    calibrated dispatch gap); 1 disables chaining; N pins the chain
    length. Ragged tails (fewer than K same-shape items buffered when
    the stream ends or the shape changes) run unchained — K=1 reuses the
    single-step executable instead of compiling a one-off tail length.
    """

    def __init__(self, step_fn: Callable[[Any], Any], *, path: str,
                 chain_k: "int | None" = None,
                 policy: "ChainPolicy | None" = None):
        import jax

        if chain_k is not None and chain_k < 1:
            raise ValueError(f"chain_k must be >= 1, got {chain_k}")
        self.step_fn = step_fn
        self.path = path
        env_k = default_chain_k()
        if chain_k is not None and env_k is not None and env_k != chain_k:
            # two explicit pins that disagree is a misconfiguration the
            # autotuner must never paper over (ISSUE 8): fail loud
            raise ValueError(
                f"conflicting chain-K pins: explicit chain_k={chain_k} "
                f"vs SPARKDL_TPU_CHAIN_K={env_k} — pin it one way, not "
                "both"
            )
        self.chain_k = chain_k if chain_k is not None else env_k
        #: True when the chain length was explicitly configured (arg or
        #: env): the autotuner registers a pinned knob and never moves it
        self.pinned = self.chain_k is not None
        self.pin_source = (
            "chain_k" if chain_k is not None
            else "SPARKDL_TPU_CHAIN_K" if env_k is not None else None
        )
        self.policy = policy if policy is not None else ChainPolicy()
        if self.chain_k is None:
            # auto mode consults policy.chain_len() per dispatch: pay the
            # 30-probe gap calibration ONCE here at construction, never
            # mid-stream on a production dispatch (or inside an engine
            # lock)
            self.policy.gap()
        self.jit_single = jax.jit(step_fn)
        self._jit_chained = jax.jit(self._chained)

    def _chained(self, *xs):
        import jax
        import jax.numpy as jnp
        from jax import lax

        stacked = jax.tree.map(lambda *leaves: jnp.stack(leaves), *xs)

        def body(i, x):
            # the carried counter keeps the body iteration-dependent so
            # XLA can never hoist/collapse identical steps (PERF.md
            # measurement discipline) — it costs one scalar add
            return i + 1, self.step_fn(x)

        _, ys = lax.scan(body, jnp.zeros((), jnp.int32), stacked)
        return ys

    # -- dispatching ---------------------------------------------------------
    def target_chain_len(self) -> int:
        """The chain length the next group aims for."""
        if self.chain_k is not None:
            return self.chain_k
        return self.policy.chain_len()

    def dispatch_single(self, x: Any) -> Any:
        """One unchained dispatch (counts toward the spine like any
        other): the probe/tail/K=1 path of :meth:`map_stream`. (The
        serving ``run_batch`` path shares :attr:`jit_single` but keeps
        its own timing/span — it must wrap the transfer inside the
        ``serving.device_step`` span and record path="serving".)"""
        import jax

        fault_point("dispatch")
        t0 = time.perf_counter()
        with span("dispatch.chain", path=self.path, k=1):
            y = self.jit_single(x)
            jax.block_until_ready(y)
        wall = time.perf_counter() - t0
        record_dispatch(self.path, 1, wall)
        self.policy.record(wall, 1)
        return y

    def dispatch_chain(self, xs: "list[Any]") -> "list[Any]":
        """Fuse ``len(xs)`` same-shape steps into one dispatch; returns
        per-step outputs in order."""
        import jax

        k = len(xs)
        if k == 1:
            return [self.dispatch_single(xs[0])]
        fault_point("dispatch")
        t0 = time.perf_counter()
        with span("dispatch.chain", path=self.path, k=k):
            ys = self._jit_chained(*xs)
            jax.block_until_ready(ys)
        wall = time.perf_counter() - t0
        record_dispatch(self.path, k, wall)
        self.policy.record(wall, k)
        return [jax.tree.map(lambda a: a[i], ys) for i in range(k)]

    def map_stream(self, it: Iterable[Any]) -> Iterator[Any]:
        """Map ``step_fn`` over a stream of device-resident inputs,
        fusing runs of same-shape items into chained dispatches; yields
        one output per input, in order.

        Buffering never reorders: a shape change (ragged tail bucket)
        flushes the pending group first. Pending items held for a chain
        are bounded by the target K, so host memory stays O(K batches).
        """
        pending: "list[Any]" = []
        pending_key = None
        for x in it:
            key = shape_key(x)
            if pending and key != pending_key:
                yield from self._flush(pending)
                pending = []
            pending.append(x)
            pending_key = key
            k = self.target_chain_len()
            if len(pending) >= k:
                if k > 1:
                    yield from self.dispatch_chain(pending)
                else:
                    yield from self._flush(pending)
                pending = []
        if pending:
            yield from self._flush(pending)

    def _flush(self, pending: "list[Any]") -> Iterator[Any]:
        """Tail/ragged flush: run unchained (no one-off-K compile)."""
        for x in pending:
            yield self.dispatch_single(x)


def chain_carry(step_fn: Callable[[Any, Any], "tuple[Any, Any]"], *,
                donate: bool = True) -> Callable:
    """Jit a carried-state K-chain: ``chained(state, stacked_batches) ->
    (state, stacked_outs)`` running ``step_fn(state, batch)`` K times in
    one dispatch (K = the stacked leading dim; jit recompiles per K).

    The carry IS the iteration dependence — steps cannot collapse — and
    ``donate=True`` donates the incoming state buffers so K optimizer
    steps update in place instead of holding two copies of the params
    (the bench_train.py discipline, productionized for
    ``train/finetune``)."""
    import jax
    from jax import lax

    def chained(state, xs):
        return lax.scan(step_fn, state, xs)

    return jax.jit(chained, donate_argnums=(0,) if donate else ())
