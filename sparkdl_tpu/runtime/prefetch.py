"""Double-buffered host→device prefetch.

TPU-side equivalent of the reference's TensorFrames block feed (SURVEY.md
2.15): while the chip computes batch i, the host stages batch i+1. The C++
Arrow bridge (sparkdl_tpu/bridge) accelerates the host-side staging when
built; this module provides the scheduling either way.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Iterable, Iterator, TypeVar

import jax

from sparkdl_tpu.observability import tracing

T = TypeVar("T")
U = TypeVar("U")

_SENTINEL = object()

_METRICS = None


def _metrics():
    """Lazy registry handles (kept off the import path so the module stays
    importable before the observability package is ready)."""
    global _METRICS
    if _METRICS is None:
        from sparkdl_tpu.observability.registry import registry

        _METRICS = (
            registry().counter(
                "sparkdl_prefetch_batches_total",
                "batches handed from the prefetch buffer to the consumer"),
            registry().histogram(
                "sparkdl_prefetch_buffer_fill",
                "buffered batches observed at each consumer take",
                # top bound covers the autotuner's depth ceiling (the
                # old top of 32 clipped every autotuned depth above it
                # into +Inf, hiding how far ahead the producer ran)
                buckets=(0, 1, 2, 3, 4, 6, 8, 16, 32, 64, 128, 256)),
            registry().histogram(
                "sparkdl_prefetch_consumer_wait_seconds",
                "consumer time blocked waiting on the producer "
                "(infeed starvation)"),
            registry().counter(
                "sparkdl_prefetch_producer_blocked_seconds_total",
                "producer time blocked on a full buffer "
                "(consumer is the bottleneck)"),
        )
    return _METRICS


class PrefetchIterator(Iterator[U]):
    """Iterator over ``transfer(item)`` with a background producer thread.

    Deterministic lifecycle for serving-style consumers that may abandon
    the stream mid-flight (a cancelled request, an errored batch):
    ``close()`` — also run by ``__del__``, exhaustion, and context-manager
    exit — sets the stop event, drains the hand-off queue so a producer
    blocked mid-put wakes up, and joins the thread. Unlike the previous
    generator implementation, release does not depend on the *generator*
    object being garbage-collected at the right moment.
    """

    def __init__(self, it: Iterable[T], size: int = 2,
                 transfer: Callable[[T], U] | None = None):
        if transfer is None:
            transfer = jax.device_put
        # maxsize=0 would make the queue unbounded (prefetch the whole
        # stream); clamp so size<=0 means minimal, not infinite, buffering.
        self._q: queue.Queue = queue.Queue(maxsize=max(1, size))
        self._size = max(1, size)
        self._err: list[BaseException] = []
        self._stop = threading.Event()
        self._done = False

        # The producer must NOT close over ``self``: the running thread
        # would then keep the iterator alive forever, so an abandoning
        # consumer's drop never triggers __del__ and the thread leaks.
        # Locals only — the thread pins just the queue/event/err cells.
        q, stop, err = self._q, self._stop, self._err

        def put(item) -> bool:
            # Bounded put so an abandoned consumer releases the producer
            # instead of leaking the thread and the device buffers queued
            # behind it.
            blocked_from: "float | None" = None
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    if blocked_from is not None:
                        _metrics()[3].inc(
                            time.monotonic() - blocked_from)
                    return True
                except queue.Full:
                    if blocked_from is None:
                        blocked_from = time.monotonic()
                    continue
            return False

        def producer():
            try:
                for item in it:
                    if stop.is_set() or not put(transfer(item)):
                        return
            except BaseException as e:  # propagate into consumer
                err.append(e)
            finally:
                put(_SENTINEL)

        self._thread = threading.Thread(
            target=producer, name="sparkdl-prefetch", daemon=True
        )
        self._thread.start()

    def __iter__(self) -> "PrefetchIterator[U]":
        return self

    def __next__(self) -> U:
        # Bounded gets so a close() from another thread (request
        # cancellation) cannot strand us: once close() drains the queue
        # the sentinel may never arrive, so re-check _done each beat.
        t0 = time.monotonic()
        while True:
            if self._done:
                if self._err:
                    # a raced close() may have drained the sentinel that
                    # carried the error: surface it, never swallow it
                    raise self._err[0]
                raise StopIteration
            try:
                item = self._q.get(timeout=0.1)
            except queue.Empty:
                if (self._err and not self._thread.is_alive()
                        and self._q.empty()):
                    # producer died (transfer/source raised) and its
                    # sentinel was lost (e.g. drained by a concurrent
                    # close, or the bounded put gave up): propagate the
                    # exception instead of spinning on an empty queue
                    self.close()
                    raise self._err[0]
                continue
            if item is _SENTINEL:
                self.close()
                if self._err:
                    raise self._err[0]
                raise StopIteration
            now = time.monotonic()
            batches, fill, wait, _ = _metrics()
            batches.inc()
            # fill AFTER the take: how far ahead the producer still is —
            # persistently 0 here == the infeed is the bottleneck
            fill.observe(self._q.qsize())
            wait.observe(now - t0)
            tracing.record_span("batch.prefetch_wait", t0, now)
            return item

    @property
    def depth(self) -> int:
        """Current buffer depth (batches the producer may run ahead)."""
        return self._size

    def set_depth(self, size: int) -> None:
        """Resize the buffer on a LIVE iterator without dropping staged
        batches (the autotuner's depth knob). Growing lets the producer
        run further ahead immediately; shrinking below the current fill
        keeps every staged batch — the producer simply blocks until the
        consumer drains under the new bound. Queue.maxsize is only read
        under the queue's own mutex, so flipping it there is exactly the
        synchronization put()/get() already use."""
        size = max(1, int(size))
        q = self._q
        with q.mutex:
            self._size = size
            q.maxsize = size
            # wake a producer parked in put(): the bound may have grown
            q.not_full.notify_all()

    def close(self) -> None:
        """Stop the producer and release queued buffers. Idempotent."""
        self._done = True
        self._stop.set()
        # Drain so a producer blocked mid-put can observe stop and exit.
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        if self._thread.is_alive():
            self._thread.join(timeout=1.0)

    def __enter__(self) -> "PrefetchIterator[U]":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self):  # GC of an abandoned iterator must not leak the thread
        try:
            self.close()
        except Exception:  # pragma: no cover - interpreter shutdown
            pass


def prefetch_to_device(
    it: Iterable[T],
    size: int = 2,
    transfer: Callable[[T], U] | None = None,
) -> PrefetchIterator[U]:
    """Run ``transfer`` (default jax.device_put) on a background thread,
    keeping ``size`` batches in flight ahead of the consumer.

    device_put is async — it returns as soon as the DMA is enqueued — so a
    depth-2 pipeline is enough to hide host→HBM transfer behind compute.
    The returned :class:`PrefetchIterator` supports ``close()`` (also run
    on GC and context-manager exit) so abandoning consumers never leak the
    producer thread.
    """
    return PrefetchIterator(it, size=size, transfer=transfer)


def pipelined_map(
    fn: Callable[[U], T],
    it: Iterable[U],
    prefetch: int = 2,
    transfer: Callable | None = None,
) -> Iterator[T]:
    """Map a (jitted) fn over batches with transfer/compute overlap.

    Because jitted calls are async, simply iterating keeps the device busy;
    the prefetch thread keeps the host side ahead.
    """
    for batch in prefetch_to_device(it, size=prefetch, transfer=transfer):
        yield fn(batch)
