"""Double-buffered host→device prefetch.

TPU-side equivalent of the reference's TensorFrames block feed (SURVEY.md
2.15): while the chip computes batch i, the host stages batch i+1. The C++
Arrow bridge (sparkdl_tpu/bridge) accelerates the host-side staging when
built; this module provides the scheduling either way.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterable, Iterator, TypeVar

import jax

T = TypeVar("T")
U = TypeVar("U")

_SENTINEL = object()


def prefetch_to_device(
    it: Iterable[T],
    size: int = 2,
    transfer: Callable[[T], U] | None = None,
) -> Iterator[U]:
    """Run ``transfer`` (default jax.device_put) on a background thread,
    keeping ``size`` batches in flight ahead of the consumer.

    device_put is async — it returns as soon as the DMA is enqueued — so a
    depth-2 pipeline is enough to hide host→HBM transfer behind compute.
    """
    if transfer is None:
        transfer = jax.device_put
    # maxsize=0 would make the queue unbounded (prefetch the whole stream);
    # clamp so size<=0 means minimal, not infinite, buffering.
    q: queue.Queue = queue.Queue(maxsize=max(1, size))
    err: list[BaseException] = []
    stop = threading.Event()

    def put(item) -> bool:
        # Bounded put so an abandoned consumer (generator closed early)
        # releases the producer instead of leaking the thread and the
        # device buffers queued behind it.
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def producer():
        try:
            for item in it:
                if not put(transfer(item)):
                    return
        except BaseException as e:  # propagate into consumer
            err.append(e)
        finally:
            put(_SENTINEL)

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    try:
        while True:
            item = q.get()
            if item is _SENTINEL:
                if err:
                    raise err[0]
                return
            yield item
    finally:
        stop.set()
        # Drain so a producer blocked mid-put can observe stop and exit.
        while not q.empty():
            try:
                q.get_nowait()
            except queue.Empty:  # pragma: no cover
                break


def pipelined_map(
    fn: Callable[[U], T],
    it: Iterable[U],
    prefetch: int = 2,
    transfer: Callable | None = None,
) -> Iterator[T]:
    """Map a (jitted) fn over batches with transfer/compute overlap.

    Because jitted calls are async, simply iterating keeps the device busy;
    the prefetch thread keeps the host side ahead.
    """
    for batch in prefetch_to_device(it, size=prefetch, transfer=transfer):
        yield fn(batch)
