from sparkdl_tpu.runtime.dtypes import DtypePolicy, default_policy, FLOAT32
from sparkdl_tpu.runtime.mesh import (
    AXIS_ORDER,
    MeshSpec,
    batch_sharding,
    data_parallel_mesh,
    replicated_sharding,
    single_device_mesh,
)
from sparkdl_tpu.runtime.batching import (
    PaddedBatch,
    default_buckets,
    pad_batch_to_multiple,
    pad_to_bucket,
    rebatch,
)
from sparkdl_tpu.runtime.completion import (
    AsyncFetcher,
    FetchTicket,
    start_fetch,
)
from sparkdl_tpu.runtime.dispatch import (
    ChainPolicy,
    ScanChainer,
    calibrate_dispatch_gap,
    chain_carry,
    overhead_share,
)
from sparkdl_tpu.runtime.prefetch import (
    PrefetchIterator,
    pipelined_map,
    prefetch_to_device,
)

__all__ = [
    "AXIS_ORDER",
    "AsyncFetcher",
    "ChainPolicy",
    "DtypePolicy",
    "FLOAT32",
    "FetchTicket",
    "MeshSpec",
    "PaddedBatch",
    "PrefetchIterator",
    "ScanChainer",
    "batch_sharding",
    "calibrate_dispatch_gap",
    "chain_carry",
    "data_parallel_mesh",
    "default_buckets",
    "default_policy",
    "overhead_share",
    "pad_batch_to_multiple",
    "pad_to_bucket",
    "pipelined_map",
    "prefetch_to_device",
    "rebatch",
    "replicated_sharding",
    "single_device_mesh",
    "start_fetch",
]
