from sparkdl_tpu.runtime.dtypes import DtypePolicy, default_policy, FLOAT32
from sparkdl_tpu.runtime.mesh import (
    AXIS_ORDER,
    MeshSpec,
    batch_sharding,
    data_parallel_mesh,
    replicated_sharding,
    single_device_mesh,
)
from sparkdl_tpu.runtime.batching import (
    PaddedBatch,
    default_buckets,
    pad_batch_to_multiple,
    pad_to_bucket,
    rebatch,
)
from sparkdl_tpu.runtime.prefetch import (
    PrefetchIterator,
    pipelined_map,
    prefetch_to_device,
)

__all__ = [
    "AXIS_ORDER",
    "DtypePolicy",
    "FLOAT32",
    "MeshSpec",
    "PaddedBatch",
    "PrefetchIterator",
    "batch_sharding",
    "data_parallel_mesh",
    "default_buckets",
    "default_policy",
    "pad_batch_to_multiple",
    "pad_to_bucket",
    "pipelined_map",
    "prefetch_to_device",
    "rebatch",
    "replicated_sharding",
    "single_device_mesh",
]
