"""Asynchronous device→host completion: overlap readback with dispatch.

PR 3 amortized *launch* overhead (one dispatch per K fused steps), but
every hot path still ended in a host-blocking ``np.asarray(out)``: the
device→host copy of batch i serialized with the dispatch of batch i+1,
and on the relayed chip one blocking read costs a full relay RTT
(~70 ms — PERF.md "Measurement discipline"). This module is the
software-pipelining half of that argument (tf.data, Murray et al.): a
result's D2H copy is *started* the moment its dispatch is enqueued
(``jax.Array.copy_to_host_async``) and *collected* only when the caller
actually needs the host value — by which point the next dispatch is
already running and the copy has landed underneath it.

Three pieces:

* :func:`start_fetch` — begin a non-blocking D2H copy of one output
  pytree and return a :class:`FetchTicket`; ``ticket.result()`` blocks
  only for whatever copy time is *left* (metered as
  ``sparkdl_fetch_wait_seconds{path=...}`` — the number that must drop
  when overlap works).
* :class:`AsyncFetcher` — the windowed form: ``submit()`` up to
  ``window`` outputs in flight (device memory stays capped at ``window``
  result buffers), ``stream()`` maps a device-output iterator to host
  results with submission order preserved and a device error surfacing
  on the result index of the batch that caused it, never at the window
  edge.
* a bounded readback thread pool (``SPARKDL_TPU_FETCH_THREADS``) as the
  fallback for leaves without ``copy_to_host_async`` — same window
  bound, same ordering contract.

Wired into every production hot path: ``BatchedRunner.run`` (results
stream out while the next chained dispatch runs),
``BatchedRunner.run_batch_async`` (the future-returning serving variant
the micro-batcher pipelines on), ``finetune`` host-metric reads, and the
continuous-GPT token readback.
"""

from __future__ import annotations

import collections
import os
import threading
import time
from concurrent.futures import (
    ThreadPoolExecutor,
    TimeoutError as FuturesTimeoutError,
)
from typing import Any, Callable, Iterable, Iterator

import numpy as np

from sparkdl_tpu.observability import tracing
from sparkdl_tpu.observability.registry import registry
from sparkdl_tpu.reliability.faults import fault_point

__all__ = [
    "AsyncFetcher",
    "FetchTicket",
    "fetch_metrics",
    "fetch_wait_seconds",
    "start_fetch",
]

_METRICS = None


def fetch_metrics():
    """Lazy handles for the completion spine (one tuple per process):
    (fetches counter by path, host-blocked-wait histogram by path,
    in-flight gauge)."""
    global _METRICS
    if _METRICS is None:
        _METRICS = (
            registry().counter(
                "sparkdl_fetches_total",
                "device->host result fetches started", labels=("path",)),
            registry().histogram(
                "sparkdl_fetch_wait_seconds",
                "host time blocked collecting an async D2H result "
                "(0-ish = the copy hid behind the next dispatch)",
                labels=("path",)),
            registry().gauge(
                "sparkdl_fetch_inflight",
                "async fetches currently in flight, all paths"),
        )
    return _METRICS


def fetch_wait_seconds(path: "str | None" = None) -> float:
    """Total host seconds blocked in ``result()`` (summed over paths when
    ``path`` is None) — the benches' ``fetch_wait_share`` numerator."""
    fam = registry().get("sparkdl_fetch_wait_seconds")
    if fam is None:
        return 0.0
    values = fam.snapshot_values()
    if path is not None:
        series = values.get(f'path="{path}"')
        return float(series["sum"]) if series else 0.0
    return float(sum(v["sum"] for v in values.values()))


_POOL: "ThreadPoolExecutor | None" = None
_POOL_LOCK = threading.Lock()


def _readback_pool() -> ThreadPoolExecutor:
    """Bounded fallback pool for leaves without ``copy_to_host_async``.

    Bounded (default 2 workers) so a burst of fallback fetches can never
    fan out into unbounded host threads — the window, not the pool,
    is the in-flight control; the pool only provides *a* background
    thread for the copy."""
    global _POOL
    with _POOL_LOCK:
        if _POOL is None:
            _POOL = ThreadPoolExecutor(
                max_workers=max(
                    1, int(os.environ.get("SPARKDL_TPU_FETCH_THREADS", "2"))
                ),
                thread_name_prefix="sparkdl-fetch",
            )
        return _POOL


def _tree_leaves(tree: Any) -> "list[Any]":
    import jax

    return jax.tree.leaves(tree)


def _to_host(tree: Any) -> Any:
    """Materialize every leaf on the host (np.asarray is a no-op for
    leaves already there). Raises the deferred device error, if any."""
    import jax

    return jax.tree.map(np.asarray, tree)


class FetchTicket:
    """One in-flight device→host fetch. ``result()`` blocks for whatever
    copy time is left, converts to host arrays, and raises the device
    error of THIS batch if its computation failed. Thread-safe and
    idempotent (the resolution is memoized)."""

    __slots__ = ("_path", "_value", "_exc", "_done", "_lock", "_future",
                 "_tree")

    def __init__(self, tree: Any, path: str, future=None):
        self._tree = tree
        self._path = path
        self._future = future  # fallback-pool future, else None
        self._value: Any = None
        self._exc: "BaseException | None" = None
        self._done = False
        self._lock = threading.Lock()

    def result(self, timeout: "float | None" = None) -> Any:
        """Host pytree of this fetch. A timeout raises
        ``concurrent.futures.TimeoutError`` and is NOT terminal — the
        fetch stays collectable (the direct path polls ``is_ready`` to
        honor the deadline; leaves without it block on the runtime)."""
        with self._lock:
            if not self._done:
                _, wait_hist, inflight = fetch_metrics()
                t0 = time.monotonic()
                finished = True
                try:
                    if self._future is not None:
                        self._value = self._future.result(timeout)
                    else:
                        if timeout is not None:
                            self._wait_ready(t0 + timeout)
                        self._value = _to_host(self._tree)
                except FuturesTimeoutError:
                    # the copy is merely not done yet: surface the
                    # timeout but leave the ticket pending/collectable
                    finished = False
                    raise
                except BaseException as e:
                    self._exc = e
                finally:
                    if finished:
                        self._done = True
                        self._tree = None  # release the device refs
                        now = time.monotonic()
                        wait_hist.observe(now - t0, path=self._path)
                        inflight.dec()
                        tracing.record_span(
                            "fetch.wait", t0, now, path=self._path)
            if self._exc is not None:
                raise self._exc
            return self._value

    def _wait_ready(self, deadline: float) -> None:
        """Poll leaf readiness until ``deadline`` so a timed ``result()``
        is honored on the direct (copy_to_host_async) path too — jax has
        no timed blocking wait, so this is a coarse is_ready poll; leaves
        without is_ready fall through to the blocking conversion."""
        leaves = [l for l in _tree_leaves(self._tree)
                  if hasattr(l, "is_ready")]
        while leaves:
            leaves = [l for l in leaves if not l.is_ready()]
            if not leaves:
                return
            if time.monotonic() >= deadline:
                raise FuturesTimeoutError(
                    f"fetch not ready within deadline "
                    f"({len(leaves)} leaf buffer(s) still in flight)"
                )
            time.sleep(0.001)

    def _release(self) -> None:
        """Abandonment path (GC of an unresolved ticket): the fetch will
        never be collected — the in-flight gauge must not leak."""
        with self._lock:
            if not self._done:
                self._done = True
                self._tree = None
                fetch_metrics()[2].dec()

    def __del__(self):  # pragma: no cover - GC timing
        try:
            self._release()
        except Exception:
            pass


def start_fetch(tree: Any, *, path: str = "default") -> FetchTicket:
    """Begin a non-blocking D2H copy of ``tree`` and return the ticket.

    Every jax-array leaf gets ``copy_to_host_async()`` — a pure hint that
    enqueues the transfer behind the leaf's computation, so the copy
    begins the moment compute finishes instead of after the host comes
    back asking. Leaves without the method (older runtimes, alternative
    array types) ride the bounded readback thread pool instead; plain
    host arrays pass through untouched either way.
    """
    fault_point("fetch")
    fetches, _, inflight = fetch_metrics()
    fetches.inc(path=path)
    inflight.inc()
    needs_pool = False
    for leaf in _tree_leaves(tree):
        if isinstance(leaf, np.ndarray) or np.isscalar(leaf):
            continue
        copy_async = getattr(leaf, "copy_to_host_async", None)
        if copy_async is None:
            needs_pool = True
            continue
        try:
            copy_async()
        except Exception:
            # the hint must never fail a fetch the blocking path could
            # serve — result() falls back to a plain np.asarray wait
            needs_pool = True
    future = _readback_pool().submit(_to_host, tree) if needs_pool else None
    return FetchTicket(tree, path, future)


class AsyncFetcher:
    """Windowed async completion: at most ``window`` results in flight.

    ``submit()`` starts one fetch; the caller keeps the returned tickets
    and resolves them in submission order (the window bound is then the
    caller's deque length — :mod:`~sparkdl_tpu.train.finetune` does
    this). :meth:`stream` is the iterator form the batch path uses::

        for host_out in AsyncFetcher(window=8, path="batch").stream(outs):
            ...  # device outputs of up to 8 batches are in flight

    Ordering/error contract (pinned by tests/runtime/test_completion.py):
    results come back in submission order, and an error raised by batch
    i's computation or readback surfaces when result i is collected —
    after results 0..i-1 were delivered, never early at the window edge.
    """

    def __init__(self, *, window: int = 2, path: str = "default"):
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        self.window = window
        self.path = path

    def submit(self, tree: Any) -> FetchTicket:
        return start_fetch(tree, path=self.path)

    def stream(self, outputs: Iterable[Any]) -> Iterator[Any]:
        """Map a device-output iterator to host results, ``window`` deep.

        Pulling from ``outputs`` is what issues the NEXT dispatch (the
        ScanChainer/jit call lives inside the source iterator), so a
        window of W keeps W results' D2H copies overlapping the following
        dispatches while device memory holds at most W result buffers.
        A source-side error (a failed dispatch) is delivered after the
        results submitted before it, on its own batch index.
        """
        pending: "collections.deque[FetchTicket]" = collections.deque()
        it = iter(outputs)
        source_exc: "BaseException | None" = None
        while True:
            try:
                out = next(it)
            except StopIteration:
                break
            except BaseException as e:
                # batches already in flight precede the failed dispatch:
                # deliver them first, then surface the error at ITS index
                source_exc = e
                break
            pending.append(self.submit(out))
            if len(pending) >= self.window:
                yield pending.popleft().result()
        while pending:
            yield pending.popleft().result()
        if source_exc is not None:
            raise source_exc
