"""SLO accounting: declared objectives, rolling error-budget burn.

An objective ("95% of requests under 250 ms, 99.9% availability") only
means something against a *window* of traffic: this module turns the
spine's existing cumulative series — the
``sparkdl_serving_latency_seconds`` histogram and the
``sparkdl_serving_requests_total{outcome}`` counter — into rolling
compliance and **burn rate** (error rate / error budget: burn 1.0 means
the budget is being consumed exactly at the sustainable pace, burn 10
means an hour of this traffic eats ten hours of budget — the
multi-window alerting quantity from the SRE literature).

No new per-request instrumentation: a :class:`SLOTracker` samples the
cumulative series on demand (every :meth:`~SLOTracker.sample` call —
``snapshot()``, a ``/metrics`` or ``/slo.json`` scrape), keeps a small
deque of (time, totals) samples, and differences the newest against the
oldest still inside ``window_s``. Latency compliance uses
:meth:`~sparkdl_tpu.observability.registry.MetricFamily.count_below`
(bucket-interpolated), so the objective threshold may sit anywhere in
the histogram's range.

Results surface three ways: ``ServingEngine.snapshot()["slo"]``, the
``sparkdl_slo_*`` gauges in ``/metrics`` (refreshed at scrape), and the
``/slo.json`` endpoint listing every registered tracker.

Note: the source series are process-wide — two engines sharing one
process share the histograms, so their trackers both see the union of
the traffic. One engine per process (the serving deployment shape) gives
exact per-engine accounting.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
import weakref
from typing import Any, Callable

from sparkdl_tpu.observability.registry import MetricsRegistry, registry

__all__ = [
    "SLO",
    "SLOTracker",
    "register",
    "sample_all",
    "slo_report",
    "unregister",
]

#: The serving series the default tracker reads (PR 2's spine names).
LATENCY_METRIC = "sparkdl_serving_latency_seconds"
REQUESTS_METRIC = "sparkdl_serving_requests_total"
#: Admission rejects (QueueFullError) never reach the outcome counter —
#: but a turned-away client is an availability failure, so the tracker
#: folds this counter into the availability denominator. Otherwise an
#: overloaded engine shedding 90% of submits would report availability
#: compliance 1.0 during exactly the incident the SLO exists to catch.
REJECTED_METRIC = "sparkdl_queue_rejected_total"
#: Per-request phase attribution (ISSUE 17): the disagg path's
#: ``{phase, tier}`` histogram. When it carries traffic the tracker
#: folds a windowed per-tier breakdown into every report, so a latency
#: burn names the GUILTY tier ("burn 4.2, 71% of request time was
#: (queue, decode)") instead of just ringing the bell.
PHASE_METRIC = "sparkdl_request_phase_seconds"

def _gauges(reg: MetricsRegistry):
    # get-or-create per sample: declaration is idempotent and samples
    # run at scrape frequency, so no handle caching is needed
    return (
        reg.gauge(
            "sparkdl_slo_objective",
            "declared objective (target fraction) per SLO dimension",
            labels=("slo", "dimension")),
        reg.gauge(
            "sparkdl_slo_compliance",
            "rolling-window compliance fraction per SLO dimension",
            labels=("slo", "dimension")),
        reg.gauge(
            "sparkdl_slo_burn_rate",
            "error-budget burn rate (error rate / budget; 1.0 = "
            "sustainable pace)",
            labels=("slo", "dimension")),
    )


@dataclasses.dataclass(frozen=True)
class SLO:
    """Declared objectives for one engine.

    ``latency_threshold_s``/``latency_target``: "``latency_target`` of
    requests complete within ``latency_threshold_s``" (None disables the
    latency dimension). ``availability_target``: fraction of requests
    that must complete without error (None disables). ``window_s`` is
    the rolling accounting window.
    """

    name: str
    latency_threshold_s: "float | None" = None
    latency_target: float = 0.95
    availability_target: "float | None" = 0.999
    window_s: float = 300.0

    def __post_init__(self):
        if not self.name:
            raise ValueError("SLO needs a name (it labels the metrics)")
        for target, what in ((self.latency_target, "latency_target"),
                             (self.availability_target,
                              "availability_target")):
            if target is not None and not (0.0 < target < 1.0):
                raise ValueError(
                    f"{what} must be in (0, 1) — a target of 1.0 has "
                    f"zero error budget; got {target}"
                )
        if self.latency_threshold_s is not None \
                and self.latency_threshold_s <= 0:
            raise ValueError(
                f"latency_threshold_s must be > 0, got "
                f"{self.latency_threshold_s}"
            )
        if self.window_s <= 0:
            raise ValueError(f"window_s must be > 0, got {self.window_s}")


class _Totals(collections.namedtuple(
        "_Totals", "t lat_good lat_total ok failed rejected phases")):
    """One cumulative sample of the source series. ``phases`` maps
    ``(phase, tier) -> (count, seconds)`` cumulative pairs from
    :data:`PHASE_METRIC` (empty when the disagg path is idle)."""


class SLOTracker:
    """Rolling error-budget accounting for one :class:`SLO`.

    ``sample()`` is the one verb: read the cumulative series, difference
    against the oldest in-window sample, publish the gauges, return the
    structured report. Thread-safe (scrapes race engine snapshots).
    """

    def __init__(self, slo: SLO, *, reg: "MetricsRegistry | None" = None,
                 clock: Callable[[], float] = time.monotonic):
        self.slo = slo
        self._reg = reg if reg is not None else registry()
        self._clock = clock
        self._lock = threading.Lock()
        self._samples: "collections.deque[_Totals]" = collections.deque()
        self._samples.append(self._read())  # the creation-time baseline

    def _read(self) -> _Totals:
        lat_good, lat_total = 0.0, 0
        if self.slo.latency_threshold_s is not None:
            fam = self._reg.get(LATENCY_METRIC)
            if fam is not None:
                lat_good, lat_total = fam.count_below(
                    self.slo.latency_threshold_s)
        ok = failed = rejected = 0.0
        fam = self._reg.get(REQUESTS_METRIC)
        if fam is not None:
            by = fam.labelled_values("outcome")
            ok = by.get("completed", 0.0)
            failed = by.get("failed", 0.0)
        fam = self._reg.get(REJECTED_METRIC)
        if fam is not None:
            values = fam.snapshot_values()
            rejected = float(values.get("", 0.0))
        phases: "dict[tuple, tuple]" = {}
        fam = self._reg.get(PHASE_METRIC)
        if fam is not None:
            for labels, stats in fam.hist_series():
                phases[(labels.get("phase", ""),
                        labels.get("tier", ""))] = (
                    stats["count"], stats["sum"])
        return _Totals(self._clock(), lat_good, lat_total, ok, failed,
                       rejected, phases)

    @staticmethod
    def _dimension(good: float, total: float, target: float) -> dict:
        """Compliance/burn report for one dimension's windowed deltas."""
        if total <= 0:
            # no traffic in the window: nothing violated, nothing burned
            return {"target": target, "requests": 0,
                    "compliance": None, "burn_rate": 0.0,
                    "budget_remaining": 1.0}
        compliance = min(1.0, max(0.0, good / total))
        burn = (1.0 - compliance) / (1.0 - target)
        return {
            "target": target,
            "requests": int(total),
            "compliance": compliance,
            "burn_rate": burn,
            "budget_remaining": max(0.0, 1.0 - burn),
        }

    def sample(self) -> "dict[str, Any]":
        with self._lock:
            cur = self._read()
            self._samples.append(cur)
            horizon = cur.t - self.slo.window_s
            while len(self._samples) >= 2 and self._samples[1].t <= horizon:
                self._samples.popleft()
            base = self._samples[0]
        # deltas clamp at 0: a registry().reset() (test isolation) makes
        # cumulative series go backwards; treat it as an empty window
        d = lambda a, b: max(0.0, a - b)  # noqa: E731
        report: "dict[str, Any]" = {
            "slo": self.slo.name,
            "window_s": self.slo.window_s,
            "latency": None,
            "availability": None,
        }
        objective, compliance_g, burn_g = _gauges(self._reg)
        if self.slo.latency_threshold_s is not None:
            dim = self._dimension(
                d(cur.lat_good, base.lat_good),
                d(cur.lat_total, base.lat_total),
                self.slo.latency_target,
            )
            dim["threshold_s"] = self.slo.latency_threshold_s
            report["latency"] = dim
            self._publish(objective, compliance_g, burn_g, "latency", dim)
        if self.slo.availability_target is not None:
            # denominator includes admission rejects (see REJECTED_METRIC)
            total = (d(cur.ok, base.ok) + d(cur.failed, base.failed)
                     + d(cur.rejected, base.rejected))
            dim = self._dimension(
                d(cur.ok, base.ok), total, self.slo.availability_target)
            dim["rejected"] = int(d(cur.rejected, base.rejected))
            report["availability"] = dim
            self._publish(objective, compliance_g, burn_g,
                          "availability", dim)
        phases = self._phase_attribution(cur, base, d)
        if phases:
            report["phases"] = phases
            # the guilty tier: where the window's request time went
            report["dominant_phase"] = {
                k: phases[0][k] for k in ("phase", "tier", "share")}
        return report

    @staticmethod
    def _phase_attribution(cur: _Totals, base: _Totals, d) -> "list[dict]":
        """Windowed per-(phase, tier) time attribution (ISSUE 17),
        largest share first — so a burning latency SLO reads which
        tier's which phase ate the window's request time."""
        rows = []
        for key, (cnt, tot) in (cur.phases or {}).items():
            b_cnt, b_tot = (base.phases or {}).get(key, (0, 0.0))
            secs = d(tot, b_tot)
            if secs > 0:
                rows.append({"phase": key[0], "tier": key[1],
                             "seconds": secs,
                             "observations": int(d(cnt, b_cnt))})
        total = sum(r["seconds"] for r in rows)
        for r in rows:
            r["share"] = r["seconds"] / total if total else 0.0
        rows.sort(key=lambda r: r["seconds"], reverse=True)
        return rows

    def _publish(self, objective, compliance, burn, dimension: str,
                 dim: dict) -> None:
        labels = {"slo": self.slo.name, "dimension": dimension}
        objective.set(dim["target"], **labels)
        compliance.set(
            dim["compliance"] if dim["compliance"] is not None else 1.0,
            **labels)
        burn.set(dim["burn_rate"], **labels)


# -- the process-wide tracker list (what /slo.json serves) --------------------

#: weak refs: the registrant (an engine's self.slo_tracker, or a test's
#: local) owns the tracker's lifetime — an engine dropped WITHOUT
#: close() self-prunes here instead of being sampled on every scrape
#: forever (same policy as flight's WeakMethod context providers)
_TRACKERS: "list[weakref.ref[SLOTracker]]" = []
_TRACKERS_LOCK = threading.Lock()


def register(tracker: SLOTracker) -> SLOTracker:
    """Add a tracker to the process list (engines register theirs at
    construction; unregister on close). Held weakly — keep a strong
    reference for as long as the SLO should be reported."""
    with _TRACKERS_LOCK:
        if not any(r() is tracker for r in _TRACKERS):
            _TRACKERS.append(weakref.ref(tracker))
    return tracker


def unregister(tracker: SLOTracker) -> None:
    with _TRACKERS_LOCK:
        _TRACKERS[:] = [r for r in _TRACKERS
                        if r() is not None and r() is not tracker]


def slo_report() -> "list[dict]":
    """Sample every registered tracker (refreshing its gauges); the
    ``/slo.json`` payload."""
    with _TRACKERS_LOCK:
        trackers = []
        live = []
        for r in _TRACKERS:
            t = r()
            if t is not None:
                trackers.append(t)
                live.append(r)
        _TRACKERS[:] = live
    out = []
    for t in trackers:
        try:
            out.append(t.sample())
        except Exception as e:  # a broken tracker must not 500 the scrape
            out.append({"slo": t.slo.name, "error": repr(e)})
    return out


def sample_all() -> None:
    """Refresh every tracker's gauges (called on /metrics scrapes so
    Prometheus sees current burn rates)."""
    slo_report()
