"""Pre-flight device/collective health probe.

SURVEY.md §5 "Failure detection": the reference inherits Spark's semantics
only — barrier stage is all-or-nothing, no health checking. The TPU build
adds a slice health check run by each TPURunner worker *after*
``jax.distributed.initialize`` and *before* the user's train_fn: if a chip
is wedged or ICI is degraded, fail fast inside the barrier task (cheap
retry) instead of 40 minutes into compilation or training.

The probe is deliberately tiny: enumerate local devices, run one addition
per device (exercises the runtime path to every chip), and one global psum
across all devices of all hosts (exercises ICI/DCN collectives end-to-end).
"""

from __future__ import annotations

import dataclasses
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass
class HealthReport:
    ok: bool
    n_local_devices: int
    n_global_devices: int
    process_index: int
    process_count: int
    platform: str
    device_kinds: list[str]
    probe_time_s: float
    collective_ok: bool
    error: str | None = None

    def summary(self) -> str:
        state = "OK" if self.ok else f"UNHEALTHY: {self.error}"
        return (
            f"[health] {state} — process {self.process_index}/"
            f"{self.process_count}, {self.n_local_devices} local / "
            f"{self.n_global_devices} global {self.platform} devices, "
            f"probe {self.probe_time_s * 1e3:.0f} ms"
        )


def check_health(*, collective: bool = True,
                 expect_local_devices: int | None = None) -> HealthReport:
    """Probe every local chip and (optionally) the global collective path.

    Raises nothing: always returns a report; caller decides whether a
    not-ok report aborts the barrier task.
    """
    t0 = time.perf_counter()
    error = None
    collective_ok = False
    local = []
    try:
        local = jax.local_devices()
        # one tiny computation per local device — catches a wedged chip
        for d in local:
            y = jax.device_put(jnp.ones((8,), jnp.float32), d) + 1.0
            np.testing.assert_allclose(np.asarray(y), 2.0)
        if expect_local_devices is not None and len(local) != expect_local_devices:
            raise RuntimeError(
                f"expected {expect_local_devices} local devices, "
                f"found {len(local)}"
            )
    except Exception as e:  # report, don't raise — caller decides
        error = f"{type(e).__name__}: {e}"
    if collective:
        # Global reduction over every device of every process: the same
        # ICI/DCN path gradient sync will take. EVERY rank enters this,
        # even one whose local probe failed — a rank that bailed out here
        # would leave its healthy peers blocked inside the collective until
        # the runtime's barrier timeout, the slow failure mode this probe
        # exists to avoid. A wedged chip either fails fast below or hangs
        # all ranks uniformly (handled by the runtime's own timeout).
        try:
            n = jax.device_count()
            mesh = jax.sharding.Mesh(np.asarray(jax.devices()), ("d",))
            ones = jax.device_put(
                jnp.ones((n,), jnp.float32),
                jax.sharding.NamedSharding(
                    mesh, jax.sharding.PartitionSpec("d")
                ),
            )
            total = float(jnp.sum(ones))  # cross-device reduction
            if total != float(n):
                raise RuntimeError(f"collective sum {total} != {n}")
            collective_ok = True
        except Exception as e:
            error = error or f"{type(e).__name__}: {e}"
    # A wedged backend can make every one of these probes raise (the exact
    # case this report exists to describe) — the "raises nothing" contract
    # means each gets an independent fallback.
    try:
        n_global = jax.device_count()
    except Exception:
        n_global = 0
    try:
        proc_idx, proc_cnt = jax.process_index(), jax.process_count()
    except Exception:
        proc_idx, proc_cnt = -1, 0
    try:
        platform = jax.default_backend()
    except Exception:
        platform = "unknown"
    return HealthReport(
        ok=error is None,
        n_local_devices=len(local),
        n_global_devices=n_global,
        process_index=proc_idx,
        process_count=proc_cnt,
        platform=platform,
        device_kinds=sorted({d.device_kind for d in local}),
        probe_time_s=time.perf_counter() - t0,
        collective_ok=collective_ok,
        error=error,
    )


def preflight(*, skip: bool = False, profiler_port: int | None = None,
              rank: int = 0) -> HealthReport | None:
    """Shared TPURunner worker pre-flight, called after
    ``jax.distributed.initialize`` on every rank.

    Runs the health probe (unless ``skip``) and raises RuntimeError on an
    unhealthy report so the barrier task fails fast; optionally starts a
    live profiler server on ``profiler_port + rank``. The *caller* resolves
    the two knobs from wherever they are authoritative — on the driver for
    the Spark backend (executor environments don't inherit the driver's),
    from the local environment for the local-process backend.
    """
    report = None
    if not skip:
        report = check_health()
        print(report.summary(), file=sys.stderr)
        if not report.ok:
            raise RuntimeError(report.summary())
    if profiler_port is not None:
        from sparkdl_tpu.observability.profiling import start_trace_server

        start_trace_server(int(profiler_port) + rank)
    # Opt-in /metrics endpoint (SPARKDL_TPU_METRICS_PORT in THIS rank's
    # env): one line to make every worker scrape-able. Per-rank port
    # offset so co-hosted ranks each get an endpoint (the profiler_port
    # convention above). Idempotent, never raises — observability must
    # not fail the job it observes.
    from sparkdl_tpu.observability.exporters import maybe_start_metrics_server

    maybe_start_metrics_server(port_offset=rank)
    return report


def preflight_env_opts() -> dict:
    """Read the preflight knobs from this process's environment (truthy
    convention, matching SPARKDL_TPU_DISABLE_NATIVE)."""
    port = os.environ.get("SPARKDL_TPU_PROFILER_PORT")
    return {
        "skip": bool(os.environ.get("SPARKDL_TPU_SKIP_HEALTH_CHECK")),
        "profiler_port": int(port) if port else None,
    }
