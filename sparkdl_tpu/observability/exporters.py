"""Registry exporters: Prometheus HTTP endpoint and periodic logline.

Three ways out of :func:`sparkdl_tpu.observability.registry.registry`:

* :class:`MetricsServer` — stdlib ``http.server`` serving the Prometheus
  text exposition on ``/metrics`` (and the JSON snapshot on
  ``/metrics.json``, SLO burn on ``/slo.json``, the reliability health
  aggregate on ``/healthz``, a live flight-recorder bundle on
  ``/debug/flight`` — ISSUE 9 — and one request's finished spans on
  ``/debug/trace/<request_id>`` — ISSUE 17); opt-in per process via
  ``SPARKDL_TPU_METRICS_PORT`` (:func:`maybe_start_metrics_server`), so
  a serving host or TPU worker becomes scrape-able with zero
  dependencies;
* ``registry().snapshot()`` — the JSON form benches and
  ``dryrun_multichip`` embed in their artifacts (no exporter needed);
* :class:`PeriodicLogEmitter` — a daemon thread logging a compact
  snapshot line every N seconds, the "no scraper, just logs" fallback
  that still beats grepping executor stdout.
"""

from __future__ import annotations

import json
import logging
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from sparkdl_tpu.observability.registry import MetricsRegistry, registry

__all__ = [
    "MetricsServer",
    "PeriodicLogEmitter",
    "maybe_start_metrics_server",
]

logger = logging.getLogger(__name__)

#: Environment knob: set to a port number to expose /metrics from this
#: process (0 = ephemeral port, logged at startup).
METRICS_PORT_ENV = "SPARKDL_TPU_METRICS_PORT"

PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class _Handler(BaseHTTPRequestHandler):
    registry: MetricsRegistry  # set by MetricsServer on the class copy

    def do_GET(self):  # noqa: N802 - BaseHTTPRequestHandler API
        path = self.path.split("?", 1)[0]
        status = 200
        try:
            if path in ("/metrics", "/"):
                self._refresh_slo_gauges()
                body = self.registry.to_prometheus().encode()
                ctype = PROMETHEUS_CONTENT_TYPE
            elif path == "/metrics.json":
                body = json.dumps(self.registry.snapshot()).encode()
                ctype = "application/json"
            elif path == "/slo.json":
                # ISSUE 9: every registered SLO tracker's rolling
                # compliance / error-budget burn, sampled at scrape time
                from sparkdl_tpu.observability import slo

                body = json.dumps(
                    {"slos": slo.slo_report()}, default=repr).encode()
                ctype = "application/json"
            elif path == "/healthz":
                # aggregate reliability state for a router-tier health
                # check: 503 only when this host cannot serve at all
                from sparkdl_tpu.observability import flight

                report = flight.healthz_report()
                status = 503 if report["status"] == "unhealthy" else 200
                body = json.dumps(report, default=repr).encode()
                ctype = "application/json"
            elif path == "/debug/flight":
                from sparkdl_tpu.observability import flight

                body = json.dumps(
                    flight.flight_recorder().debug_view(),
                    default=repr).encode()
                ctype = "application/json"
            elif path.startswith("/debug/trace/"):
                # ISSUE 17: one request's finished spans from THIS
                # process's ring, keyed by request id (= trace id) —
                # the single-host half of fleet_trace()
                from sparkdl_tpu.observability import tracing

                try:
                    rid = int(path.rsplit("/", 1)[1])
                except ValueError:
                    self.send_error(
                        400, "request id must be an integer")
                    return
                body = json.dumps({
                    "request_id": rid,
                    "host_hash": tracing.host_hash(),
                    "now_us": tracing.trace_clock_us(),
                    "spans": tracing.spans_for_trace(rid),
                }, default=repr).encode()
                ctype = "application/json"
            else:
                self.send_error(404)
                return
        except Exception:
            logger.exception("exporter: %s handler failed", path)
            self.send_error(500)
            return
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _refresh_slo_gauges(self):
        """Refresh sparkdl_slo_* gauges so a Prometheus scrape of
        /metrics sees current burn rates (trackers are pull-sampled)."""
        from sparkdl_tpu.observability import slo

        slo.sample_all()

    def log_message(self, fmt, *args):  # scrapes must not spam stdout
        logger.debug("metrics scrape: " + fmt, *args)


class MetricsServer:
    """Serve the registry over HTTP from a daemon thread.

    >>> srv = MetricsServer(port=0)          # ephemeral port
    >>> urllib.request.urlopen(f"http://127.0.0.1:{srv.port}/metrics")
    >>> srv.close()
    """

    def __init__(self, port: int = 0, host: str = "",
                 reg: "MetricsRegistry | None" = None):
        # per-instance handler subclass so two servers (tests) can carry
        # different registries
        handler = type("_BoundHandler", (_Handler,),
                       {"registry": reg if reg is not None else registry()})
        self._httpd = ThreadingHTTPServer((host, port), handler)
        self._httpd.daemon_threads = True
        self._closed = False
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.2},
            name="sparkdl-metrics-http", daemon=True,
        )
        self._thread.start()

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def closed(self) -> bool:
        return self._closed

    def close(self) -> None:
        self._closed = True
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=2)

    def __enter__(self) -> "MetricsServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


_autostart_lock = threading.Lock()
_autostarted: "MetricsServer | None" = None


def maybe_start_metrics_server(port_offset: int = 0) -> "MetricsServer | None":
    """Start the process's /metrics endpoint iff ``SPARKDL_TPU_METRICS_PORT``
    is set. Idempotent (one server per process) and never raises — a taken
    port logs a warning rather than failing the job it observes.

    ``port_offset`` is added to the configured port (0 stays 0: an
    ephemeral port needs no offset) — the per-rank spread worker
    preflights use so co-hosted ranks don't fight over one port, same
    convention as ``SPARKDL_TPU_PROFILER_PORT + rank``."""
    global _autostarted
    port_s = os.environ.get(METRICS_PORT_ENV)
    if not port_s:
        return None
    with _autostart_lock:
        # a caller that close()d the shared server relinquishes it; the
        # next request starts a fresh one instead of returning a corpse
        if _autostarted is not None and not _autostarted.closed:
            return _autostarted
        try:
            port = int(port_s)
            _autostarted = MetricsServer(
                port=port + port_offset if port else 0
            )
        # OverflowError: int() accepts e.g. 99999 but bind() rejects
        # ports outside 0-65535 with OverflowError, not OSError
        except (OSError, OverflowError, ValueError) as e:
            logger.warning(
                "%s=%s: metrics endpoint not started (%s)",
                METRICS_PORT_ENV, port_s, e,
            )
            return None
        logger.info("serving /metrics on port %d", _autostarted.port)
        return _autostarted


class PeriodicLogEmitter:
    """Log a compact registry snapshot every ``interval_s`` seconds.

    One JSON object per line under the ``sparkdl_tpu.metrics`` logger —
    greppable from Spark executor logs, which is exactly the observability
    floor the reference left us at (SURVEY.md §5), now structured.
    """

    def __init__(self, interval_s: float = 60.0,
                 log: "logging.Logger | None" = None,
                 reg: "MetricsRegistry | None" = None):
        if interval_s <= 0:
            raise ValueError(f"interval_s must be > 0, got {interval_s}")
        self.interval_s = interval_s
        self._log = log if log is not None else \
            logging.getLogger("sparkdl_tpu.metrics")
        self._registry = reg if reg is not None else registry()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="sparkdl-metrics-log", daemon=True
        )
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.emit()

    def emit(self) -> None:
        snap = self._registry.snapshot()
        if snap:
            self._log.info("metrics %s", json.dumps(snap, sort_keys=True))

    def close(self, *, final_emit: bool = True) -> None:
        self._stop.set()
        self._thread.join(timeout=2)
        if final_emit:
            self.emit()

    def __enter__(self) -> "PeriodicLogEmitter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
