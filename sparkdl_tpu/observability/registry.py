"""Process-wide metrics registry: counters, gauges, bucketed histograms.

The single spine every layer reports into (ISSUE 2): serving admission
and latency, prefetch buffer occupancy, batching pad waste, training step
times, checkpoint save/restore — one ``registry()`` call surfaces all of
it as a JSON snapshot, Prometheus exposition text, or a periodic logline
(:mod:`sparkdl_tpu.observability.exporters`).

Zero-dep and thread-safe by construction: stdlib only (imported by
modules that must not pull jax, e.g. ``runtime.batching`` helpers before
a backend exists), one lock per metric family, label children resolved
once and cached so hot paths pay a dict hit + a float add.

Naming follows the Prometheus conventions: ``*_total`` counters,
``*_seconds`` histograms, lowercase snake-case label names. Histograms
are fixed-boundary cumulative buckets; ``snapshot()`` derives p50/p95/p99
by linear interpolation inside the owning bucket — coarse but monotone,
and free at scrape time.
"""

from __future__ import annotations

import math
import re
import threading
from typing import Iterable, Mapping

_NAME_RE = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"[a-zA-Z_][a-zA-Z0-9_]*$")

#: Default histogram bucket upper bounds, tuned for seconds-scale
#: latencies from ~100µs device dispatches to multi-second restores.
DEFAULT_BUCKETS: tuple[float, ...] = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
)

#: Bucket set for percentage-valued histograms (occupancy, utilization).
PERCENT_BUCKETS: tuple[float, ...] = (
    5.0, 10.0, 20.0, 30.0, 40.0, 50.0, 60.0, 70.0, 80.0, 90.0, 95.0, 100.0,
)


def _escape_label_value(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _label_key(label_names: "tuple[str, ...]",
               labels: Mapping[str, object]) -> "tuple[str, ...]":
    if set(labels) != set(label_names):
        raise ValueError(
            f"labels {sorted(labels)} do not match declared label names "
            f"{sorted(label_names)}"
        )
    return tuple(str(labels[n]) for n in label_names)


def _render_labels(label_names: "tuple[str, ...]",
                   values: "tuple[str, ...]") -> str:
    return ",".join(
        f'{n}="{_escape_label_value(v)}"'
        for n, v in zip(label_names, values)
    )


class _Hist:
    """One histogram series: cumulative-at-render fixed buckets + sum."""

    __slots__ = ("counts", "sum", "n")

    def __init__(self, n_bounds: int):
        self.counts = [0] * (n_bounds + 1)  # last cell = +Inf overflow
        self.sum = 0.0
        self.n = 0


class _Bound:
    """A metric family bound to one label-value tuple (hot-path handle)."""

    __slots__ = ("_family", "_key")

    def __init__(self, family: "MetricFamily", key: "tuple[str, ...]"):
        self._family = family
        self._key = key

    def inc(self, value: float = 1.0) -> None:
        self._family._inc(self._key, value)

    def dec(self, value: float = 1.0) -> None:
        self._family._set_delta(self._key, -value)

    def set(self, value: float) -> None:
        self._family._set(self._key, value)

    def observe(self, value: float) -> None:
        self._family._observe(self._key, value)


class GaugeShare:
    """One contributor's share of a SUMMED process gauge.

    Several live objects (request queues, KV block pools) can feed the
    same gauge; each pushes *deltas* of its own value so neighbors are
    never clobbered. ``registry().reset()`` (test isolation) zeroes the
    gauge under every contributor — the generation stamp restarts this
    contributor's baseline at 0 instead of pushing a stale negative
    delta. Call :meth:`set` with the contributor's CURRENT value; call
    ``set(0)`` on close to retract the contribution.

    Not self-locking: callers serialize their own ``set`` (the queue's
    condition lock, the serving-engine lock).
    """

    def __init__(self, family: "MetricFamily"):
        self._family = family
        self._reported = 0.0
        self._gen = registry().generation

    def set(self, value: float) -> None:
        gen = registry().generation
        if gen != self._gen:
            self._reported = 0.0
            self._gen = gen
        if value != self._reported:
            self._family.inc(value - self._reported)
            self._reported = value


class MetricFamily:
    """One named metric (counter/gauge/histogram) with 0+ label dims."""

    def __init__(self, name: str, kind: str, help: str = "",
                 label_names: Iterable[str] = (),
                 buckets: "tuple[float, ...] | None" = None):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        label_names = tuple(label_names)
        for ln in label_names:
            if not _LABEL_RE.match(ln):
                raise ValueError(f"invalid label name {ln!r}")
        if kind not in ("counter", "gauge", "histogram"):
            raise ValueError(f"unknown metric kind {kind!r}")
        self.name = name
        self.kind = kind
        self.help = help
        self.label_names = label_names
        if kind == "histogram":
            bounds = tuple(sorted(buckets if buckets is not None
                                  else DEFAULT_BUCKETS))
            if not bounds:
                raise ValueError("histogram needs at least one bucket bound")
            self.bucket_bounds: "tuple[float, ...]" = bounds
        else:
            if buckets is not None:
                raise ValueError(f"buckets= is histogram-only, not {kind}")
            self.bucket_bounds = ()
        self._lock = threading.Lock()
        self._series: "dict[tuple[str, ...], float | _Hist]" = {}
        self._bound: "dict[tuple[str, ...], _Bound]" = {}

    # -- label binding -------------------------------------------------------
    def labels(self, **labels: object) -> _Bound:
        """Resolve (and cache) the child series for one label-value set."""
        key = _label_key(self.label_names, labels)
        with self._lock:
            b = self._bound.get(key)
            if b is None:
                b = self._bound[key] = _Bound(self, key)
            return b

    def _default_key(self) -> "tuple[str, ...]":
        if self.label_names:
            raise ValueError(
                f"metric {self.name} declares labels {self.label_names}; "
                "use .labels(...) or pass them as keyword arguments"
            )
        return ()

    # -- recording (family-level conveniences) -------------------------------
    def inc(self, value: float = 1.0, **labels: object) -> None:
        key = (_label_key(self.label_names, labels) if labels
               else self._default_key())
        self._inc(key, value)

    def dec(self, value: float = 1.0, **labels: object) -> None:
        key = (_label_key(self.label_names, labels) if labels
               else self._default_key())
        self._set_delta(key, -value)

    def set(self, value: float, **labels: object) -> None:
        key = (_label_key(self.label_names, labels) if labels
               else self._default_key())
        self._set(key, value)

    def observe(self, value: float, **labels: object) -> None:
        key = (_label_key(self.label_names, labels) if labels
               else self._default_key())
        self._observe(key, value)

    # -- storage -------------------------------------------------------------
    def _inc(self, key: "tuple[str, ...]", value: float) -> None:
        if self.kind == "counter" and value < 0:
            raise ValueError("counters only go up; use a gauge")
        if self.kind == "histogram":
            raise ValueError(f"{self.name} is a histogram; use observe()")
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + value

    def _set_delta(self, key: "tuple[str, ...]", delta: float) -> None:
        if self.kind != "gauge":
            raise ValueError(f"{self.name} is a {self.kind}; dec() is "
                             "gauge-only")
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + delta

    def _set(self, key: "tuple[str, ...]", value: float) -> None:
        if self.kind != "gauge":
            raise ValueError(f"{self.name} is a {self.kind}; set() is "
                             "gauge-only")
        with self._lock:
            self._series[key] = float(value)

    def _observe(self, key: "tuple[str, ...]", value: float) -> None:
        if self.kind != "histogram":
            raise ValueError(f"{self.name} is a {self.kind}; observe() is "
                             "histogram-only")
        with self._lock:
            h = self._series.get(key)
            if h is None:
                h = self._series[key] = _Hist(len(self.bucket_bounds))
            # first bound whose upper edge holds the value (bisect would
            # win past ~64 buckets; linear wins at the ~17 we ship)
            i = 0
            for i, b in enumerate(self.bucket_bounds):
                if value <= b:
                    break
            else:
                i = len(self.bucket_bounds)
            h.counts[i] += 1
            h.sum += value
            h.n += 1

    # -- readout -------------------------------------------------------------
    def _hist_percentile(self, h: _Hist, p: float) -> "float | None":
        """p in [0,100] by linear interpolation inside the owning bucket."""
        if h.n == 0:
            return None
        rank = (p / 100.0) * h.n
        cum = 0
        lo = 0.0
        for i, c in enumerate(h.counts):
            if c == 0:
                if i < len(self.bucket_bounds):
                    lo = self.bucket_bounds[i]
                continue
            if cum + c >= rank:
                hi = (self.bucket_bounds[i]
                      if i < len(self.bucket_bounds) else lo)
                frac = (rank - cum) / c
                return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
            cum += c
            if i < len(self.bucket_bounds):
                lo = self.bucket_bounds[i]
        return lo

    def _copy_series(self) -> "list[tuple[tuple[str, ...], float | _Hist]]":
        """Consistent point-in-time copy: _Hist objects are mutable, so
        they are deep-copied UNDER the lock — a scrape racing observe()
        must never see sum/count/buckets torn mid-update."""
        with self._lock:
            out = []
            for key, v in self._series.items():
                if isinstance(v, _Hist):
                    c = _Hist(len(self.bucket_bounds))
                    c.counts = list(v.counts)
                    c.sum, c.n = v.sum, v.n
                    v = c
                out.append((key, v))
            return out

    def snapshot_values(self) -> dict:
        out = {}
        for key, v in self._copy_series():
            label_str = _render_labels(self.label_names, key)
            if isinstance(v, _Hist):
                out[label_str] = {
                    "count": v.n,
                    "sum": v.sum,
                    "mean": (v.sum / v.n) if v.n else None,
                    "p50": self._hist_percentile(v, 50),
                    "p95": self._hist_percentile(v, 95),
                    "p99": self._hist_percentile(v, 99),
                }
            else:
                out[label_str] = v
        return out

    def count_below(self, bound: float) -> "tuple[float, int]":
        """Histogram-only: ``(observations <= bound, total observations)``
        summed across every label series, interpolating linearly inside
        the bucket that straddles ``bound`` — the same linearity as the
        percentile readout, and the SLO tracker's compliance source
        ("what fraction of requests beat the latency objective").
        Overflow-bucket observations (> the last finite bound) are never
        counted good: conservative when the objective exceeds the bucket
        range."""
        if self.kind != "histogram":
            raise ValueError(
                f"{self.name} is a {self.kind}; count_below() is "
                "histogram-only"
            )
        good = 0.0
        total = 0
        for _key, v in self._copy_series():
            total += v.n
            lo = 0.0
            for i, b in enumerate(self.bucket_bounds):
                c = v.counts[i]
                if bound >= b:
                    good += c
                else:
                    if bound > lo and b > lo:
                        good += c * (bound - lo) / (b - lo)
                    break
                lo = b
        return good, total

    def hist_series(self) -> "list[tuple[dict, dict]]":
        """Histogram-only structured readout: one ``(labels, stats)``
        pair per label series, where ``labels`` maps label name → value
        and ``stats`` is ``{count, sum, mean, p50, p95, p99}`` — the
        accessor programmatic consumers (per-tier SLO attribution, the
        bench's phase breakdown) use instead of parsing rendered
        ``snapshot_values`` label strings (a format coupling)."""
        if self.kind != "histogram":
            raise ValueError(
                f"{self.name} is a {self.kind}; hist_series() is "
                "histogram-only"
            )
        out = []
        for key, v in self._copy_series():
            out.append((dict(zip(self.label_names, key)), {
                "count": v.n,
                "sum": v.sum,
                "mean": (v.sum / v.n) if v.n else None,
                "p50": self._hist_percentile(v, 50),
                "p95": self._hist_percentile(v, 95),
                "p99": self._hist_percentile(v, 99),
            }))
        return out

    def labelled_values(self, label: str) -> dict:
        """Scalar series keyed by ONE label dimension's value —
        the structured accessor for programmatic consumers (parsing the
        rendered ``snapshot_values`` label strings is a format
        coupling). Series that collide on the chosen dimension (the
        family has other label dimensions too) are summed, never
        silently overwritten. Histogram series are skipped."""
        idx = self.label_names.index(label)
        out: dict = {}
        for key, v in self._copy_series():
            if isinstance(v, _Hist):
                continue
            out[key[idx]] = out.get(key[idx], 0.0) + v
        return out

    def render_prometheus(self, lines: "list[str]") -> None:
        items = sorted(self._copy_series())
        if not items:
            return
        if self.help:
            lines.append(f"# HELP {self.name} {self.help}")
        lines.append(f"# TYPE {self.name} {self.kind}")
        for key, v in items:
            label_str = _render_labels(self.label_names, key)
            if not isinstance(v, _Hist):
                sfx = "{%s}" % label_str if label_str else ""
                lines.append(f"{self.name}{sfx} {_fmt(v)}")
                continue
            cum = 0
            for i, bound in enumerate(self.bucket_bounds):
                cum += v.counts[i]
                ls = (label_str + "," if label_str else "") + \
                    f'le="{_fmt(bound)}"'
                lines.append(f"{self.name}_bucket{{{ls}}} {cum}")
            ls = (label_str + "," if label_str else "") + 'le="+Inf"'
            lines.append(f"{self.name}_bucket{{{ls}}} {v.n}")
            sfx = "{%s}" % label_str if label_str else ""
            lines.append(f"{self.name}_sum{sfx} {_fmt(v.sum)}")
            lines.append(f"{self.name}_count{sfx} {v.n}")


def _fmt(v: float) -> str:
    """Prometheus value formatting: integral floats render bare."""
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if float(v) == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


class MetricsRegistry:
    """Collection of :class:`MetricFamily` keyed by name.

    ``counter``/``gauge``/``histogram`` are get-or-create: the first call
    declares the family (help text, label names, buckets), later calls
    return the same object and must agree on kind and label names —
    mismatches raise instead of silently splitting a metric.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._families: "dict[str, MetricFamily]" = {}
        #: bumped by reset(); delta-reporting instrumentation (e.g. the
        #: queue-depth gauge) compares it to know its baseline was wiped
        self.generation = 0

    # -- declaration ---------------------------------------------------------
    def counter(self, name: str, help: str = "",
                labels: Iterable[str] = ()) -> MetricFamily:
        return self._get_or_create(name, "counter", help, labels, None)

    def gauge(self, name: str, help: str = "",
              labels: Iterable[str] = ()) -> MetricFamily:
        return self._get_or_create(name, "gauge", help, labels, None)

    def histogram(self, name: str, help: str = "",
                  labels: Iterable[str] = (),
                  buckets: "tuple[float, ...] | None" = None) -> MetricFamily:
        return self._get_or_create(name, "histogram", help, labels, buckets)

    def _get_or_create(self, name, kind, help, labels, buckets):
        labels = tuple(labels)
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = self._families[name] = MetricFamily(
                    name, kind, help=help, label_names=labels,
                    buckets=buckets,
                )
                return fam
        if fam.kind != kind or fam.label_names != labels:
            raise ValueError(
                f"metric {name} already registered as {fam.kind} with "
                f"labels {fam.label_names}; requested {kind} with {labels}"
            )
        # buckets=None means "whatever it was declared with"; an explicit
        # disagreeing set would silently land observations in boundaries
        # the caller never asked for
        if buckets is not None and tuple(sorted(buckets)) != fam.bucket_bounds:
            raise ValueError(
                f"histogram {name} already registered with buckets "
                f"{fam.bucket_bounds}; requested {tuple(sorted(buckets))}"
            )
        return fam

    def get(self, name: str) -> "MetricFamily | None":
        with self._lock:
            return self._families.get(name)

    # -- export --------------------------------------------------------------
    def snapshot(self) -> dict:
        """JSON-able point-in-time view of every series.

        ``{name: {"type": ..., "help": ..., "values": {label_str: value}}}``
        — histogram values are ``{count, sum, mean, p50, p95, p99}`` dicts.
        Families with no recorded series are omitted (declaring a metric
        is free; only activity shows up).
        """
        with self._lock:
            fams = list(self._families.values())
        out = {}
        for fam in fams:
            values = fam.snapshot_values()
            if values:
                out[fam.name] = {
                    "type": fam.kind,
                    "help": fam.help,
                    "values": values,
                }
        return out

    def to_prometheus(self) -> str:
        """Prometheus/OpenMetrics text exposition of every series."""
        lines: "list[str]" = []
        with self._lock:
            fams = sorted(self._families.values(), key=lambda f: f.name)
        for fam in fams:
            fam.render_prometheus(lines)
        return "\n".join(lines) + ("\n" if lines else "")

    def reset(self) -> None:
        """Zero every series, KEEPING declarations (test isolation).

        Instrumented modules cache family handles at import; dropping the
        families would orphan those handles, so reset clears values only.
        The generation bump tells delta-reporting callers their previously
        pushed contributions are gone.
        """
        with self._lock:
            fams = list(self._families.values())
            self.generation += 1
        for fam in fams:
            with fam._lock:
                fam._series.clear()


#: The process-global registry every layer reports into.
_REGISTRY = MetricsRegistry()


def registry() -> MetricsRegistry:
    """The process-wide :class:`MetricsRegistry` (ISSUE 2's single spine)."""
    return _REGISTRY


def flatten_snapshot(snap: "dict | None" = None) -> "dict[str, float]":
    """Flatten ``registry().snapshot()`` to ``{series_key: float}``.

    Key shape: ``name{labels}`` for scalars, ``name{labels}:field`` for
    histogram fields — the flat numeric dict
    :func:`sparkdl_tpu.observability.metrics.aggregate_across_hosts`
    reduces across hosts.
    """
    if snap is None:
        snap = registry().snapshot()
    flat: "dict[str, float]" = {}
    for name, fam in snap.items():
        for label_str, v in fam["values"].items():
            key = f"{name}{{{label_str}}}" if label_str else name
            if isinstance(v, dict):
                for field, fv in v.items():
                    if isinstance(fv, (int, float)):
                        flat[f"{key}:{field}"] = float(fv)
            elif isinstance(v, (int, float)):
                flat[key] = float(v)
    return flat


def snapshot_across_hosts() -> dict:
    """All-hosts mean/min/max of every numeric series (jax collective —
    must be called by every process of the job, like any collective).

    ``aggregate_across_hosts`` requires an IDENTICAL key set on every
    host, but registries diverge under data-dependent instrumentation (a
    failure counter only exists on the host that saw a failure), so the
    key sets are unioned first — two cheap allgathers of the serialized
    key list — and missing series ride as None (NaN in the reduce).

    The runner epilogue (``TPURunner(metrics_summary=True)``) and
    multi-host benches use this so per-host registries roll up to one
    driver-visible dict via the same ``aggregate_across_hosts`` that
    reduces StepMeter summaries.
    """
    import jax

    from sparkdl_tpu.observability.metrics import aggregate_across_hosts

    flat = flatten_snapshot()
    if jax.process_count() > 1:
        flat = {k: flat.get(k) for k in _allgather_key_union(flat)}
    return aggregate_across_hosts(flat)


def _allgather_key_union(flat: "dict[str, float]") -> "list[str]":
    """Union of every host's metric keys (collective; identical result on
    all hosts). Keys ship as length-padded utf-8 — process_allgather only
    moves same-shape arrays, so lengths are exchanged first."""
    import numpy as np
    from jax.experimental import multihost_utils

    blob = np.frombuffer(
        "\n".join(sorted(flat)).encode(), np.uint8
    )
    lengths = multihost_utils.process_allgather(
        np.asarray([blob.size], np.int64)
    ).reshape(-1)
    width = int(lengths.max())
    if width == 0:
        return []
    padded = np.zeros((width,), np.uint8)
    padded[: blob.size] = blob
    gathered = multihost_utils.process_allgather(padded)
    union: "set[str]" = set()
    for row, n in zip(gathered, lengths):
        if n:
            union.update(bytes(row[: int(n)]).decode().split("\n"))
    return sorted(union)
