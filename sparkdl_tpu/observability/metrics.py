"""Step-time / throughput / MFU / infeed meters.

The headline numbers this framework is scored on are images/sec/chip and
MFU (BASELINE.md targets); this module is where they are measured, the same
way in tests, benches and production runs.

MFU definition used throughout: ``achieved FLOP/s / peak FLOP/s``, with
achieved = (model FLOPs per step, from XLA's compiled cost analysis or a
caller-supplied analytic count) / measured step wall time, and peak = the
per-chip matrix-unit peak for the platform x dtype, times chips. This is
*model* FLOPs utilization (the "How to Scale Your Model" convention), not
hardware-counter utilization — rematerialized FLOPs don't inflate it when
the caller supplies the analytic count.
"""

from __future__ import annotations

import collections
import statistics
import time
from typing import Any, Callable

import jax

#: Peak dense matmul FLOP/s per chip. bf16 figures from public TPU/GPU
#: datasheets; fp32 is the bf16 number /2 on TPU (the MXU computes in bf16
#: with fp32 accumulate; pure-fp32 runs at half rate on v4/v5).
_PEAK_FLOPS: dict[str, float] = {
    # TPU generations (per chip, bf16)
    "v6e": 918e12,
    "v5p": 459e12,
    "v5e": 197e12,
    "v5": 197e12,
    "v4": 275e12,
    "v3": 123e12,
    "v2": 46e12,
}


def percentile(values: "list[float] | tuple[float, ...]",
               p: float) -> float | None:
    """Linear-interpolated percentile (numpy's default method), stdlib-only
    so meters never pay an array round-trip for a scalar.

    ``p`` in [0, 100]; returns None on an empty sample.
    """
    return _percentile_sorted(sorted(values), p)


def _percentile_sorted(s: "list[float]", p: float) -> float | None:
    """percentile() on an already-sorted sample (one sort, many ps)."""
    if not 0 <= p <= 100:
        raise ValueError(f"percentile must be in [0, 100], got {p}")
    if not s:
        return None
    if len(s) == 1:
        return float(s[0])
    rank = (p / 100.0) * (len(s) - 1)
    lo = int(rank)
    hi = min(lo + 1, len(s) - 1)
    frac = rank - lo
    return float(s[lo] * (1.0 - frac) + s[hi] * frac)


def device_peak_flops(device: "jax.Device | None" = None,
                      dtype: str = "bf16") -> float | None:
    """Best-effort peak FLOP/s of one chip; None when unknown (CPU, etc.)."""
    if device is None:
        device = jax.devices()[0]
    kind = getattr(device, "device_kind", "").lower()
    peak = None
    for tag, flops in _PEAK_FLOPS.items():
        if tag in kind.replace(" ", ""):
            peak = flops
            break
    if peak is None and "tpu" in kind:
        peak = _PEAK_FLOPS["v5e"]  # conservative default for unknown TPUs
    if peak is not None and dtype in ("f32", "fp32", "float32"):
        peak /= 2
    return peak


def compiled_flops(fn: Callable, *args: Any, **kwargs: Any) -> float | None:
    """FLOPs of one call of jitted ``fn`` per XLA's cost analysis.

    Returns None when the backend doesn't report cost analysis. ``fn`` may
    already be jitted or plain; args may be concrete arrays or
    ShapeDtypeStructs (lowering is abstract either way).
    """
    try:
        jitted = fn if hasattr(fn, "lower") else jax.jit(fn)
        compiled = jitted.lower(*args, **kwargs).compile()
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):  # older jax returns [dict]
            cost = cost[0] if cost else {}
        flops = cost.get("flops")
        return float(flops) if flops and flops > 0 else None
    except Exception:
        return None


class StepMeter:
    """Accumulates per-step timings into throughput / MFU / infeed metrics.

    Usage inside a training or inference loop::

        meter = StepMeter(flops_per_example=..., n_chips=jax.device_count())
        for batch in data:
            with meter.step(examples=len(batch)):
                out = step_fn(state, batch)
                jax.block_until_ready(out)
            # optionally: meter.note_infeed_wait(seconds)

    ``summary()`` returns the structured per-host metrics dict SURVEY.md §5
    calls for (step time, examples/sec/chip, infeed-starvation %, MFU).
    """

    def __init__(self, *, flops_per_example: float | None = None,
                 flops_per_step: float | None = None,
                 n_chips: int | None = None,
                 peak_flops_per_chip: float | None = None,
                 window: int = 50, warmup_steps: int = 1):
        self.flops_per_example = flops_per_example
        self.flops_per_step = flops_per_step
        self.n_chips = n_chips if n_chips is not None else jax.device_count()
        self.peak_flops_per_chip = (
            peak_flops_per_chip
            if peak_flops_per_chip is not None
            else device_peak_flops()
        )
        self.warmup_steps = warmup_steps
        self._times = collections.deque(maxlen=window)
        self._examples = collections.deque(maxlen=window)
        self._infeed = collections.deque(maxlen=window)
        self._seen = 0
        self._total_examples = 0

    # -- recording -----------------------------------------------------------
    class _StepCtx:
        def __init__(self, meter: "StepMeter", examples: int):
            self._m, self._ex = meter, examples

        def __enter__(self):
            self._t0 = time.perf_counter()
            return self

        def __exit__(self, exc_type, *exc):
            if exc_type is None:
                self._m.record(time.perf_counter() - self._t0, self._ex)

    def step(self, examples: int = 0) -> "_StepCtx":
        return StepMeter._StepCtx(self, examples)

    def record(self, step_time_s: float, examples: int = 0,
               infeed_wait_s: float = 0.0) -> None:
        self._seen += 1
        if self._seen <= self.warmup_steps:  # compile step poisons the mean
            return
        self._times.append(step_time_s)
        self._examples.append(examples)
        self._infeed.append(infeed_wait_s)
        self._total_examples += examples

    def note_infeed_wait(self, seconds: float) -> None:
        """Attribute host-input stall time to the most recent step."""
        if self._infeed:
            self._infeed[-1] += seconds

    # -- derived metrics -----------------------------------------------------
    @property
    def steps_recorded(self) -> int:
        return len(self._times)

    def mean_step_time(self) -> float | None:
        return statistics.fmean(self._times) if self._times else None

    def step_time_percentile(self, p: float) -> float | None:
        """Percentile of recorded step times over the window (p in
        [0, 100]); None until a step is recorded."""
        return percentile(list(self._times), p)

    def step_time_percentiles(
        self, ps: "tuple[float, ...]" = (50, 95, 99)
    ) -> dict[str, float | None]:
        """The serving-latency trio (p50/p95/p99 by default) off a single
        sort of the window — what ``serving.metrics`` reports per
        request."""
        s = sorted(self._times)
        return {f"p{p:g}": _percentile_sorted(s, p) for p in ps}

    def examples_per_sec(self) -> float | None:
        t = sum(self._times)
        return sum(self._examples) / t if t > 0 else None

    def examples_per_sec_per_chip(self) -> float | None:
        eps = self.examples_per_sec()
        return eps / self.n_chips if eps is not None else None

    def infeed_starvation_pct(self) -> float | None:
        t = sum(self._times)
        return 100.0 * sum(self._infeed) / t if t > 0 else None

    def achieved_flops_per_sec(self) -> float | None:
        t = sum(self._times)
        if t <= 0:
            return None
        if self.flops_per_step is not None:
            return self.flops_per_step * len(self._times) / t
        if self.flops_per_example is not None:
            return self.flops_per_example * sum(self._examples) / t
        return None

    def mfu(self) -> float | None:
        achieved = self.achieved_flops_per_sec()
        peak = self.peak_flops_per_chip
        if achieved is None or not peak:
            return None
        return achieved / (peak * self.n_chips)

    def summary(self) -> dict[str, float | int | None]:
        return {
            "steps": self.steps_recorded,
            "total_examples": self._total_examples,
            "step_time_mean_s": self.mean_step_time(),
            "examples_per_sec": self.examples_per_sec(),
            "examples_per_sec_per_chip": self.examples_per_sec_per_chip(),
            "infeed_starvation_pct": self.infeed_starvation_pct(),
            "mfu": self.mfu(),
            "n_chips": self.n_chips,
        }


def aggregate_across_hosts(metrics: dict[str, float | None]) -> dict:
    """All-hosts mean/min/max of each numeric metric, identical on every
    host (SURVEY.md §5: per-host metrics aggregated to the driver).

    Single-process (the common test path) returns mean=min=max=value.
    """
    import numpy as np

    # Key set must be identical on every host or the allgather misaligns
    # (a straggler host with None metrics would otherwise ship fewer
    # columns) — so keep ALL keys and encode missing values as NaN, then
    # reduce with the nan-aware ops.
    keys = sorted(metrics.keys())
    local = np.asarray(
        [
            float(metrics[k])
            if isinstance(metrics[k], (int, float)) else np.nan
            for k in keys
        ],
        np.float64,
    )
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        stacked = multihost_utils.process_allgather(local)
    else:
        stacked = local[None]
    out: dict[str, dict[str, float]] = {}
    for i, k in enumerate(keys):
        col = stacked[:, i]
        col = col[~np.isnan(col)]
        if col.size == 0:
            continue
        out[k] = {
            "mean": float(col.mean()),
            "min": float(col.min()),
            "max": float(col.max()),
        }
    return out
