"""Flight recorder: a bounded ring of structured events + postmortems.

The spine (registry.py) answers "how is the system doing in aggregate";
the tracing ring answers "where did this request's time go". Neither
answers "why did replica 1 get quarantined at 14:02" — by the time an
operator asks, the causal chain (the fault injection, the retry storm,
the autotune decision that shrank the buffer) has scrolled out of the
logs. This module keeps that chain: every reliability-relevant event
(span completions, autotune decisions, retry attempts, fault
injections, quarantine/probation/watchdog transitions, checkpoint
fallbacks, failed requests) lands in ONE process-wide bounded ring, and
reliability triggers (:class:`~sparkdl_tpu.serving.replicas.HungDispatchError`,
replica quarantine, ``CheckpointCorruptError``,
``AllReplicasQuarantinedError``) automatically dump a **postmortem
bundle** — last-N events, registry snapshot, per-replica/engine state
from registered context providers, and the in-flight requests' traces —
to a configurable directory (``SPARKDL_TPU_FLIGHT_DIR``) and the
``/debug/flight`` endpoint.

Contracts:

* **Lock-cheap append.** :func:`record_event` is one dict build + a
  ``deque.append`` (+ an ``itertools.count`` bump) — no lock, well under
  a microsecond (guarded by run-tests.sh next to the fault_point guard).
  Recording is always on; the ring is the bound.
* **Triggers settle before dumping.** A trigger schedules the dump
  ``settle_s`` (default 0.25 s) later so the postmortem captures the
  *recovery* that followed — the re-routed batch completing, the
  probation probe — not just the instant of failure. Triggers inside
  that window (and within ``min_interval_s`` of the last dump) coalesce
  instead of storming the disk.
* **Observability must not crash the job.** Context providers and dump
  writes are exception-guarded; a failing provider lands as an error
  entry in the bundle, never as an exception on a serving thread.

The same context-provider registry feeds :func:`healthz_report` — the
``/healthz`` aggregation the future router tier health-checks: live
replica quarantine/probation state, retry-budget remaining, and the
last checkpoint-integrity verdict (pushed via :func:`set_health_fact`
by the checkpoint manager).
"""

from __future__ import annotations

import collections
import itertools
import json
import logging
import os
import re
import threading
import time
import weakref
from typing import Any, Callable

from sparkdl_tpu.observability.registry import registry

__all__ = [
    "ENV_DIR",
    "FlightRecorder",
    "add_context_provider",
    "adopt_incident",
    "current_incident_id",
    "flight_recorder",
    "healthz_report",
    "record_event",
    "remove_context_provider",
    "set_health_fact",
    "trigger_dump",
]

_log = logging.getLogger(__name__)

#: Postmortem bundles land here when set (dumps stay in-memory-only,
#: served at /debug/flight, when unset).
ENV_DIR = "SPARKDL_TPU_FLIGHT_DIR"
#: Ring capacity override (events retained in memory).
ENV_EVENTS = "SPARKDL_TPU_FLIGHT_EVENTS"
#: Minimum seconds between postmortem dumps (trigger storms coalesce).
ENV_MIN_INTERVAL = "SPARKDL_TPU_FLIGHT_MIN_INTERVAL_S"

#: Tracing events included in a bundle (the tail of the span ring).
_BUNDLE_TRACE_EVENTS = 512
#: In-flight request traces resolved per bundle (cap: dump cost bound).
_BUNDLE_MAX_TRACES = 32

_M_DUMPS = None


def _dumps_counter():
    global _M_DUMPS
    if _M_DUMPS is None:
        _M_DUMPS = registry().counter(
            "sparkdl_flight_dumps_total",
            "postmortem bundles written by the flight recorder",
            labels=("reason",))
    return _M_DUMPS


_UNSET = object()


def safe_ring_snapshot(ring) -> "list[dict]":
    """Copy a hot-append ring: ``list(deque)`` raises RuntimeError if a
    producer appends mid-copy, and a postmortem/scrape must get the
    ring, not an exception. Shared by the flight rings and the tracing
    event ring."""
    for _ in range(3):
        try:
            return list(ring)
        except RuntimeError:  # pragma: no cover - hot-append race
            continue
    return []  # pragma: no cover


class FlightRecorder:
    """Bounded ring of structured events + postmortem bundle writer.

    One process-wide instance (:func:`flight_recorder`) is what
    production code feeds; tests may build isolated instances. All
    configuration is mutable post-construction via :meth:`configure`
    (benches and the chaos smoke shrink ``settle_s``).
    """

    def __init__(self, capacity: int = 4096, *,
                 directory: "str | None" = None,
                 settle_s: float = 0.25,
                 min_interval_s: float = 10.0,
                 max_bundles: int = 16):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self._ring: "collections.deque[dict]" = collections.deque(
            maxlen=capacity)
        #: span completions are orders of magnitude more frequent than
        #: reliability events when tracing is on — they get their OWN
        #: ring so a span storm can never evict the sparse causal chain
        #: (quarantines, faults, retries) the postmortem exists for
        self._span_ring: "collections.deque[dict]" = collections.deque(
            maxlen=capacity)
        self._seq = itertools.count(1)  # CPython-atomic event counter
        self.directory = directory
        self.settle_s = settle_s
        self.min_interval_s = min_interval_s
        self.max_bundles = max_bundles
        self.last_bundle: "dict | None" = None
        self.last_path: "str | None" = None
        self._trigger_lock = threading.Lock()
        self._last_dump_mono: float = -float("inf")
        self._pending: "threading.Timer | None" = None
        #: cross-host postmortem correlation (ISSUE 17): one incident id
        #: spans every bundle this process writes within ``incident_ttl_s``
        #: of the first trigger, and rides the KV-handoff wire so the
        #: PEER tier's bundles carry the SAME id — /debug/flight output
        #: from both hosts joins on it.
        self.incident_ttl_s = 60.0
        self._incident_id: "str | None" = None
        self._incident_at: float = -float("inf")

    # -- the hot path --------------------------------------------------------
    def record(self, kind: str, **fields: Any) -> None:
        """Append one event. Lock-free (one dict + deque.append): sits on
        retry/fault/span-completion paths, so it must stay ~sub-µs."""
        ev = {"seq": next(self._seq), "t": time.time(), "kind": kind}
        if fields:
            ev.update(fields)
        self._ring.append(ev)

    def record_span_event(self, name: str, **fields: Any) -> None:
        """Append one span completion to the dedicated span ring (fed by
        ``tracing._finish``; same cost contract as :meth:`record`).

        Deliberately overlaps the tracing ring: that ring is the
        export surface and is user-clearable (``clear_trace()``), while
        the flight recorder is the always-available black box — a
        bundle taken after a trace export/clear still shows recent span
        activity. The dedicated ring (vs the reliability ring) is what
        keeps a tracing-on span storm from evicting the sparse causal
        chain."""
        ev = {"seq": next(self._seq), "t": time.time(), "kind": "span",
              "name": name}
        if fields:
            ev.update(fields)
        self._span_ring.append(ev)

    @property
    def events_total(self) -> int:
        """Events recorded since process start, both rings (monotone;
        survives ring eviction — it is the sequence counter, not the
        ring length)."""
        tails = [int(r[-1]["seq"])
                 for r in (self._ring, self._span_ring) if r]
        return max(tails, default=0)

    def events(self, last: "int | None" = None) -> "list[dict]":
        """Snapshot of the reliability-event ring (oldest first);
        ``last`` trims to the newest N. Best-effort consistent (the ring
        is append-only)."""
        evs = safe_ring_snapshot(self._ring)
        return evs[-last:] if last else evs

    def span_events(self, last: "int | None" = None) -> "list[dict]":
        """Snapshot of the span-completion ring (oldest first)."""
        evs = safe_ring_snapshot(self._span_ring)
        return evs[-last:] if last else evs

    # -- incident correlation ------------------------------------------------
    def current_incident_id(self) -> "str | None":
        """The live incident id, or None once ``incident_ttl_s`` has
        passed since the last trigger/adoption — reliability events
        separated by a quiet minute are different incidents."""
        with self._trigger_lock:
            if time.monotonic() - self._incident_at > self.incident_ttl_s:
                return None
            return self._incident_id

    def adopt_incident(self, incident_id: "str | None") -> None:
        """Join an incident another host started: a KV handoff (or any
        cross-host payload) carrying an incident id stamps it here, so
        THIS host's next bundle shares the id and the two tiers'
        ``/debug/flight`` output is joinable. A live local incident is
        never overwritten — first writer wins, both sides converge on
        the oldest id in the causal chain."""
        if not incident_id:
            return
        with self._trigger_lock:
            now = time.monotonic()
            if self._incident_id is None \
                    or now - self._incident_at > self.incident_ttl_s:
                self._incident_id = str(incident_id)
            self._incident_at = now

    def reset_incident(self) -> None:
        """Close the live incident window (test isolation, or an
        operator declaring the incident over): the next trigger mints a
        FRESH id instead of extending this one's TTL."""
        with self._trigger_lock:
            self._incident_id = None
            self._incident_at = -float("inf")

    def _ensure_incident_locked(self) -> str:
        now = time.monotonic()
        if self._incident_id is None \
                or now - self._incident_at > self.incident_ttl_s:
            self._incident_id = f"inc-{os.getpid():x}-{next(self._seq)}"
        self._incident_at = now
        return self._incident_id

    # -- configuration -------------------------------------------------------
    def configure(self, *, directory: Any = _UNSET,
                  settle_s: "float | None" = None,
                  min_interval_s: "float | None" = None,
                  capacity: "int | None" = None,
                  max_bundles: "int | None" = None) -> "FlightRecorder":
        if directory is not _UNSET:
            self.directory = directory
        if settle_s is not None:
            self.settle_s = settle_s
        if min_interval_s is not None:
            self.min_interval_s = min_interval_s
        if max_bundles is not None:
            self.max_bundles = max_bundles
        if capacity is not None and capacity != self._ring.maxlen:
            self._ring = collections.deque(self.events(), maxlen=capacity)
            self._span_ring = collections.deque(
                self.span_events(), maxlen=capacity)
        return self

    # -- postmortems ---------------------------------------------------------
    def dump(self, reason: str, *, extra: "dict | None" = None) -> dict:
        """Build (but do not write) a postmortem bundle: last-N events,
        registry snapshot, every context provider's state, the tail of
        the tracing ring, and the spans of every in-flight request any
        provider reports (``inflight_request_ids``)."""
        from sparkdl_tpu.observability import tracing

        context: "dict[str, Any]" = {}
        inflight: "list[int]" = []
        for name, fn in _providers_snapshot():
            try:
                out = fn()
            except Exception as e:
                out = {"error": repr(e)}
            context[name] = out
            if isinstance(out, dict):
                try:
                    inflight.extend(
                        int(r) for r in out.get("inflight_request_ids") or ()
                    )
                except Exception:  # provider gave junk: keep the rest
                    pass
        # one snapshot of the span ring, shared by the tail copy and
        # every in-flight trace resolution (resolving 32 traces against
        # a 100k-event ring must not copy it 32 times mid-incident)
        all_traces = tracing.trace_events()
        bundle = {
            "reason": reason,
            "incident_id": self.current_incident_id(),
            "time_unix": time.time(),
            "pid": os.getpid(),
            "events_total": self.events_total,
            "events": self.events(),
            "span_events": self.span_events(_BUNDLE_TRACE_EVENTS),
            "registry": registry().snapshot(),
            "context": context,
            "trace_events": all_traces[-_BUNDLE_TRACE_EVENTS:],
            "inflight_traces": {
                str(rid): tracing.spans_for_trace(rid, events=all_traces)
                for rid in inflight[:_BUNDLE_MAX_TRACES]
            },
        }
        if extra:
            bundle["extra"] = extra
        return bundle

    def write_postmortem(self, reason: str, *,
                         extra: "dict | None" = None) -> "str | None":
        """Build a bundle, keep it as :attr:`last_bundle`, and write it
        to :attr:`directory` (pruned to ``max_bundles``) when one is
        configured. Returns the file path (None with no directory).

        A triggered postmortem IS an incident: one is minted here if
        none is live, so every bundle carries an ``incident_id`` and
        bundles from correlated failures (this host's, and — via the
        handoff wire's adoption — the peer tier's) share it."""
        with self._trigger_lock:
            self._ensure_incident_locked()
        bundle = self.dump(reason, extra=extra)
        self.last_bundle = bundle
        _dumps_counter().inc(reason=reason)
        path = None
        if self.directory:
            slug = re.sub(r"[^a-zA-Z0-9_.-]+", "_", reason)[:48]
            path = os.path.join(
                self.directory, f"flight-{time.time_ns()}-{slug}.json"
            )
            os.makedirs(self.directory, exist_ok=True)
            with open(path, "w") as f:
                # default=repr: provider state may carry numpy scalars /
                # device objects; a postmortem must never fail to write
                json.dump(bundle, f, default=repr)
            self.last_path = path
            self._prune()
            _log.error(
                "flight recorder: postmortem bundle (%s, %d events) "
                "written to %s", reason, len(bundle["events"]), path,
            )
        else:
            _log.error(
                "flight recorder: postmortem (%s, %d events) captured "
                "in memory — set %s to persist bundles",
                reason, len(bundle["events"]), ENV_DIR,
            )
        return path

    def _prune(self) -> None:
        try:
            bundles = sorted(
                f for f in os.listdir(self.directory)
                if f.startswith("flight-") and f.endswith(".json")
            )
            for stale in bundles[:-self.max_bundles]:
                os.unlink(os.path.join(self.directory, stale))
        except OSError:  # pragma: no cover - dir vanished mid-prune
            pass

    def trigger_dump(self, reason: str, *,
                     settle_s: "float | None" = None,
                     **fields: Any) -> None:
        """Reliability trigger: record the event now, write the
        postmortem after ``settle_s`` (so the bundle captures the
        recovery that follows — re-routes, probation), coalescing
        triggers inside the settle window and rate-limited to one dump
        per ``min_interval_s``. Never raises and never blocks the
        caller beyond the event append with a settle window; a
        ``settle_s=0`` override dumps INLINE before returning — what a
        trigger whose caller is about to raise a process-fatal error
        (checkpoint corruption) must use, or the daemon timer dies with
        the interpreter and the flagship postmortem is never written.
        The explicit override also BYPASSES coalescing and the rate
        limit (cancelling any pending settle timer): "the recent bundle
        covers this" is never true for a dump whose process is about to
        die. A recorder merely *configured* with ``settle_s=0`` (tests)
        keeps normal rate-limiting."""
        # the incident starts at the TRIGGER, not at the settled dump:
        # payloads crossing hosts inside the settle window must already
        # carry the id for the peer's bundle to join on
        with self._trigger_lock:
            incident = self._ensure_incident_locked()
        self.record("trigger", reason=reason, incident_id=incident,
                    **fields)
        force_inline = settle_s is not None and settle_s <= 0
        if settle_s is None:
            settle_s = self.settle_s
        pending = None
        with self._trigger_lock:
            now = time.monotonic()
            if force_inline:
                pending, self._pending = self._pending, None
                self._last_dump_mono = now
            else:
                if self._pending is not None:
                    return  # coalesced into the already-scheduled dump
                if now - self._last_dump_mono < self.min_interval_s:
                    return  # rate-limited: the recent bundle covers this
                self._last_dump_mono = now
                if settle_s <= 0:
                    timer = None
                else:
                    timer = threading.Timer(
                        settle_s, self._scheduled_dump, args=(reason,)
                    )
                    timer.daemon = True
                    self._pending = timer
        if force_inline:
            if pending is not None:
                pending.cancel()
            self._scheduled_dump(reason)
        elif timer is not None:
            timer.start()
        else:
            self._scheduled_dump(reason)

    def _scheduled_dump(self, reason: str) -> None:
        with self._trigger_lock:
            self._pending = None
            self._last_dump_mono = time.monotonic()
        try:
            self.write_postmortem(reason)
        except Exception:  # pragma: no cover - observability never crashes
            _log.exception("flight recorder: postmortem dump failed")

    def debug_view(self) -> dict:
        """The ``/debug/flight`` payload: a live bundle built on demand
        plus the location of the last written postmortem."""
        return {
            "last_postmortem_path": self.last_path,
            "bundle": self.dump("debug.scrape"),
        }


#: The process-wide recorder every instrumentation point feeds.
_RECORDER = FlightRecorder(
    capacity=int(os.environ.get(ENV_EVENTS, "4096")),
    directory=os.environ.get(ENV_DIR) or None,
    min_interval_s=float(os.environ.get(ENV_MIN_INTERVAL, "10")),
)


def flight_recorder() -> FlightRecorder:
    return _RECORDER


def record_event(kind: str, **fields: Any) -> None:
    """Append one event to the process flight ring (the hot-path form)."""
    _RECORDER.record(kind, **fields)


def trigger_dump(reason: str, *, settle_s: "float | None" = None,
                 **fields: Any) -> None:
    """Fire a reliability trigger on the process recorder
    (``settle_s=0`` dumps inline — see the method)."""
    _RECORDER.trigger_dump(reason, settle_s=settle_s, **fields)


def current_incident_id() -> "str | None":
    """The process recorder's live incident id (None outside one) —
    what the KV-handoff export stamps onto the wire (ISSUE 17)."""
    return _RECORDER.current_incident_id()


def adopt_incident(incident_id: "str | None") -> None:
    """Join an incident that rode in over the wire (see the method)."""
    _RECORDER.adopt_incident(incident_id)


# -- context providers --------------------------------------------------------

_PROVIDERS: "dict[str, Callable[[], Callable[[], dict] | None]]" = {}
_PROVIDERS_LOCK = threading.Lock()


def add_context_provider(name: str, fn: Callable[[], dict]) -> str:
    """Register a zero-arg callable contributing live state to every
    postmortem bundle and to :func:`healthz_report` (engines and replica
    pools register their ``snapshot``-shaped views; remove on close).
    Bound methods are held via :class:`weakref.WeakMethod`, so an engine
    dropped WITHOUT close() is still garbage-collectable — its entry
    self-prunes instead of pinning the engine (and its model arrays)
    for the process lifetime. Returns ``name`` (the removal handle)."""
    ref = (weakref.WeakMethod(fn) if hasattr(fn, "__self__")
           else (lambda fn=fn: fn))
    with _PROVIDERS_LOCK:
        _PROVIDERS[name] = ref
    return name


def remove_context_provider(name: str) -> None:
    with _PROVIDERS_LOCK:
        _PROVIDERS.pop(name, None)


def _providers_snapshot() -> "list[tuple[str, Callable[[], dict]]]":
    out = []
    with _PROVIDERS_LOCK:
        for name, ref in list(_PROVIDERS.items()):
            fn = ref()
            if fn is None:  # provider owner was garbage-collected
                _PROVIDERS.pop(name)
            else:
                out.append((name, fn))
    return out


# -- health facts + /healthz aggregation --------------------------------------

_FACTS: "dict[str, Any]" = {}
_FACTS_LOCK = threading.Lock()


def set_health_fact(key: str, value: Any) -> None:
    """Publish one slow-changing health fact (e.g. the checkpoint
    manager's last integrity verdict) for /healthz and postmortems."""
    with _FACTS_LOCK:
        _FACTS[key] = value


def health_facts() -> "dict[str, Any]":
    with _FACTS_LOCK:
        return dict(_FACTS)


def healthz_report() -> dict:
    """Aggregate reliability state for a router-tier health check.

    ``status`` is ``ok`` / ``degraded`` / ``unhealthy``:

    * **unhealthy** — some replica pool has ZERO healthy replicas, or
      the last checkpoint restore found digest-verified corruption with
      no intact fallback (verdict ``corrupt``, not pinned): this host
      cannot currently serve / resume. ``/healthz`` answers 503.
    * **degraded** — a pool is serving with quarantined replicas, the
      process retry budget ran dry, a serving engine's KV block pool is
      on an exhaustion streak (admissions deferring — self-recovering
      as slots retire, hence never ``unhealthy``), an elastic
      autoscaler is mid-incident (a scale decision was vetoed by SLO
      burn or deferred by a fault — state ``vetoed``/``deferred``,
      self-clearing once the controller recovers), or the last restore
      fell back past a torn checkpoint / failed ambiguously
      (``fallback`` / ``unreadable`` / pinned-step ``corrupt``): route
      around if possible, still serving.
    * **ok** — everything else (including "no pools registered").

    A provider that RAISES lands under ``provider_errors`` (never in
    ``replica_pools`` — its shape is unknown) and forces at least
    ``degraded``: state that cannot be observed must not read as
    healthy.
    """
    pools = []
    kv_pools = []
    autoscalers = []
    errors = []
    status = "ok"
    for name, fn in _providers_snapshot():
        try:
            out = fn()
        except Exception as e:
            errors.append({"provider": name, "error": repr(e)})
            continue
        if isinstance(out, dict) and isinstance(
                out.get("autoscaler"), dict):
            a = out["autoscaler"]
            autoscalers.append({"provider": name, **a})
            if a.get("state") in ("vetoed", "deferred") \
                    and status == "ok":
                # a scale event is mid-incident (reverted by SLO burn,
                # or deferred by a fault): degraded, never unhealthy —
                # the controller retries/recovers on its own cadence
                status = "degraded"
            kv_as = a.get("kv")
            if (isinstance(kv_as, dict)
                    and int(kv_as.get("shrink_blocked_streak") or 0) > 0
                    and status == "ok"):
                # scale-down is deferring because parked sessions hold
                # unpark reservations (ROADMAP item 1): degraded, never
                # unhealthy — clears when sessions resume or drop
                status = "degraded"
        if isinstance(out, dict) and isinstance(out.get("kv_pool"), dict):
            kvp = out["kv_pool"]
            tiers = kvp.get("tiers") if isinstance(
                kvp.get("tiers"), dict) else None
            kv_pools.append({
                "provider": name,
                "blocks_total": kvp.get("blocks_total"),
                "blocks_used": kvp.get("blocks_used"),
                "blocks_cached": kvp.get("blocks_cached"),
                "deferrals_total": kvp.get("deferrals_total"),
                "exhausted_streak": kvp.get("exhausted_streak"),
                # tier occupancy (ROADMAP item 1): how much of this
                # engine's session state sits in the cheap tiers
                **({"host_tier_blocks": tiers.get("host_blocks"),
                    "disk_tier_blocks": tiers.get("disk_blocks"),
                    "parked_sessions": tiers.get("parked_sessions"),
                    } if tiers is not None else {}),
            })
            if int(kvp.get("exhausted_streak") or 0) > 0 \
                    and status == "ok":
                # admissions are deferring on an exhausted block pool:
                # degraded, never unhealthy — it self-recovers as slots
                # retire and free their blocks
                status = "degraded"
        if not (isinstance(out, dict) and "healthy_count" in out):
            continue  # engine-level providers: not a pool view
        healthy = int(out.get("healthy_count") or 0)
        total = int(out.get("replica_count") or 0)
        pools.append({
            "provider": name,
            "replica_count": total,
            "healthy_count": healthy,
            "quarantined_count": total - healthy,
        })
        if healthy == 0 and total > 0:
            status = "unhealthy"
        elif healthy < total and status == "ok":
            status = "degraded"
    if errors and status == "ok":
        status = "degraded"
    from sparkdl_tpu.reliability.retry import process_retry_budget

    budget = process_retry_budget()
    if budget.remaining == 0 and status == "ok":
        status = "degraded"
    facts = health_facts()
    ck = facts.get("checkpoint_integrity")
    if isinstance(ck, dict):
        verdict = ck.get("verdict")
        if verdict == "corrupt" and not ck.get("pinned"):
            status = "unhealthy"
        elif verdict in ("fallback", "unreadable", "corrupt") \
                and status == "ok":
            status = "degraded"
    ov = facts.get("overload")
    if (isinstance(ov, dict) and int(ov.get("level") or 0) > 0
            and status == "ok"):
        # the brownout ladder is above normal (ISSUE 20): degraded,
        # never unhealthy — the host is deliberately shedding load and
        # steps back down on its own hysteresis
        status = "degraded"
    return {
        "status": status,
        "overload": ov if isinstance(ov, dict) else None,
        "replica_pools": pools,
        "kv_pools": kv_pools,
        "autoscalers": autoscalers,
        "provider_errors": errors,
        "retry_budget": {
            "remaining": budget.remaining,
            "initial": budget.initial,
        },
        "checkpoint_integrity": ck,
        "flight": {
            "events_total": _RECORDER.events_total,
            "last_postmortem_path": _RECORDER.last_path,
        },
    }
