"""Fleet-wide observability plane: cross-host scraping and trace
stitching (ISSUE 17).

Per-host observability stops at the process boundary: each serving host
has its own ``/metrics``, its own SLO trackers, and its own tracing ring
whose timestamps are microseconds since *that process's* monotonic
epoch — three hosts are three unrelated clocks. A disaggregated request
(prefill on host A, KV handoff over the wire, decode on host B) leaves
span fragments on every host it touched; answering "where did THIS
request's time go" needs all of them on ONE timeline.

:class:`FleetScraper` is that plane. It polls every registered
:class:`~sparkdl_tpu.fabric.host.HostHandle` over the SAME surface the
router routes over (``capacity()``/``snapshot()``/``health()`` plus the
``trace()`` RPC this PR adds), so anything the fabric can route to, the
observability plane can observe — in-process handles and HTTP
transports alike.

Clock-skew correction: every ``trace()`` RPC returns the remote host's
trace-clock reading (``now_us``, µs since its epoch) taken while
serving the call. The scraper brackets the RPC with its own clock and
estimates the remote offset as ``remote_now − round-trip midpoint`` —
the classic NTP offset estimate, best-of-N probes keeping the
minimum-RTT sample (the midpoint assumption degrades with asymmetric
latency, so the tightest round trip wins). ``fleet_trace`` subtracts
each host's offset from its fragments' timestamps, deduplicates by
span id (hosts sharing one process share one ring), and returns a
single ordered timeline that loads in ui.perfetto.dev via
:meth:`~FleetScraper.export_fleet_trace`. Offset error is bounded by
RTT/2 — sub-millisecond on a LAN, which is the resolution caveat to
keep in mind when reading µs-level gaps across hosts.

Phase attribution: the decode tier's ``handoff.wire`` span carries the
request's measured phase durations as attributes, so
:func:`stitch_phase_breakdown` reads the five-phase breakdown (queue
wait → prefill compute → handoff wire → decode queue → decode compute)
straight off the stitched trace; the phases telescope, so their sum is
the request's end-to-end latency.
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any

from sparkdl_tpu.observability.registry import registry

__all__ = [
    "FleetScraper",
    "FleetServer",
    "stitch_phase_breakdown",
]

_log = logging.getLogger(__name__)

_M_SCRAPES = registry().counter(
    "sparkdl_fleet_scrapes_total",
    "fleet-aggregator scrapes served, by endpoint "
    "(metrics/slo/healthz/trace)",
    labels=("endpoint",))
_M_FLEET_HOSTS = registry().gauge(
    "sparkdl_fleet_hosts",
    "hosts registered with the fleet scraper")
_M_HOST_UP = registry().gauge(
    "sparkdl_fleet_host_up",
    "1 if the host answered the last fleet poll, 0 if it errored",
    labels=("host",))
_M_CLOCK_OFFSET = registry().gauge(
    "sparkdl_fleet_clock_offset_seconds",
    "estimated trace-clock offset of each host relative to the "
    "scraper (RPC round-trip midpoint method; error bounded by RTT/2)",
    labels=("host",))
_M_STITCHED = registry().counter(
    "sparkdl_fleet_stitched_traces_total",
    "cross-host trace stitches served by fleet_trace()")

#: The five telescoping request phases, in wall order. Shared with the
#: run-tests.sh contract checks so the sum-equals-e2e assert and this
#: module can never disagree about what "all phases" means.
PHASES = (
    ("queue", "prefill"),
    ("compute", "prefill"),
    ("wire", "handoff"),
    ("queue", "decode"),
    ("compute", "decode"),
)


def stitch_phase_breakdown(spans: "list[dict]") -> "list[dict] | None":
    """Five-phase latency attribution from one stitched span timeline.

    Anchors on the ``handoff.wire`` span (recorded on the decode host,
    carrying the measured phase durations as attributes — see
    ``DecodeWorker._admit_handoff``); decode compute is the remainder
    of the timeline after the derived admit instant, i.e. exactly the
    engine's admit→done interval on the decode host's own clock. None
    for a trace with no tier crossing (a colocated request has no
    phases to split)."""
    wire = [e for e in spans if e.get("name") == "handoff.wire"]
    if not wire:
        return None
    w = wire[-1]  # a re-crossed (requeued) request: the final crossing
    a = w.get("args") or {}
    end_us = max(
        (float(e.get("ts", 0.0)) + float(e.get("dur", 0.0))
         for e in spans),
        default=float(w.get("ts", 0.0)))
    wire_s = float(a.get("wire_s", 0.0))
    dq_s = float(a.get("decode_queue_s", 0.0))
    # admit instant on the stitched timeline: wire start + wire + queue
    t_adm_us = float(w.get("ts", 0.0)) + (wire_s + dq_s) * 1e6
    seconds = {
        ("queue", "prefill"): float(a.get("queue_wait_s", 0.0)),
        ("compute", "prefill"): float(a.get("prefill_s", 0.0)),
        ("wire", "handoff"): wire_s,
        ("queue", "decode"): dq_s,
        ("compute", "decode"): max(0.0, (end_us - t_adm_us) / 1e6),
    }
    return [{"phase": p, "tier": t, "seconds": seconds[(p, t)]}
            for p, t in PHASES]


class FleetScraper:
    """Poll a fleet of :class:`HostHandle`-shaped hosts and aggregate
    (see module docstring). Hosts register with :meth:`add_host` (or
    wholesale via :meth:`from_router` / :meth:`from_phase_router`);
    anything with ``host_id``/``capacity()``/``health()``/``trace()``
    qualifies — tests duck-type fake hosts with rigged clocks.

    ``probes`` is the per-host clock-probe count (best of N by minimum
    RTT); offsets cache until :meth:`clock_offsets` is asked to
    refresh, since monotonic-clock *rates* agree even when epochs
    don't."""

    def __init__(self, *, probes: int = 3):
        if probes < 1:
            raise ValueError(f"probes must be >= 1, got {probes}")
        self.probes = probes
        self._lock = threading.Lock()
        self._hosts: "dict[str, Any]" = {}
        self._tiers: "dict[str, str]" = {}
        self._offsets_us: "dict[str, float]" = {}

    # -- registration ---------------------------------------------------------
    def add_host(self, handle: Any, *, tier: "str | None" = None) -> str:
        host_id = str(handle.host_id)
        with self._lock:
            self._hosts[host_id] = handle
            if tier is not None:
                self._tiers[host_id] = tier
            _M_FLEET_HOSTS.set(len(self._hosts))
        return host_id

    def remove_host(self, host_id: str) -> None:
        with self._lock:
            self._hosts.pop(host_id, None)
            self._tiers.pop(host_id, None)
            self._offsets_us.pop(host_id, None)
            _M_FLEET_HOSTS.set(len(self._hosts))

    def hosts(self) -> "dict[str, Any]":
        with self._lock:
            return dict(self._hosts)

    def tier_of(self, host_id: str) -> "str | None":
        with self._lock:
            return self._tiers.get(host_id)

    @classmethod
    def from_router(cls, router: Any, **kwargs) -> "FleetScraper":
        """One scraper over everything a
        :class:`~sparkdl_tpu.fabric.router.Router` routes to."""
        scraper = cls(**kwargs)
        for handle in router.fleet_hosts().values():
            scraper.add_host(handle)
        return scraper

    @classmethod
    def from_phase_router(cls, phase_router: Any, **kwargs) -> "FleetScraper":
        """One scraper over a disaggregated deployment's BOTH tiers,
        host→tier mapping included (feeds per-tier aggregation)."""
        scraper = cls(**kwargs)
        for tier, router in (("prefill", phase_router.prefill),
                             ("decode", phase_router.decode)):
            for handle in router.fleet_hosts().values():
                scraper.add_host(handle, tier=tier)
        return scraper

    # -- clock-offset estimation ----------------------------------------------
    def _probe_offset_us(self, handle: Any) -> float:
        """Best-of-N offset estimate for one host (see module
        docstring): each probe brackets a ``trace()`` RPC with the
        local trace clock and keeps the minimum-RTT sample's
        ``remote_now − midpoint``."""
        from sparkdl_tpu.observability import tracing

        best_rtt = None
        best_offset = 0.0
        for _ in range(self.probes):
            t0 = tracing.trace_clock_us()
            out = handle.trace(0)  # an id no host ever mints: [] spans
            t1 = tracing.trace_clock_us()
            remote_now = out.get("now_us")
            if remote_now is None:
                continue
            rtt = t1 - t0
            if best_rtt is None or rtt < best_rtt:
                best_rtt = rtt
                best_offset = float(remote_now) - (t0 + t1) / 2.0
        return best_offset

    def clock_offsets(self, *, refresh: bool = False) -> "dict[str, float]":
        """Per-host trace-clock offsets in µs (``host clock − scraper
        clock``). Cached after first estimation — pass ``refresh=True``
        to re-probe (e.g. after a host restart changed its epoch)."""
        for host_id, handle in self.hosts().items():
            with self._lock:
                if not refresh and host_id in self._offsets_us:
                    continue
            try:
                off = self._probe_offset_us(handle)
            except Exception:
                _log.debug("fleet: clock probe failed for %s", host_id,
                           exc_info=True)
                continue
            with self._lock:
                self._offsets_us[host_id] = off
            _M_CLOCK_OFFSET.set(off / 1e6, host=host_id)
        with self._lock:
            return dict(self._offsets_us)

    # -- trace stitching ------------------------------------------------------
    def fleet_trace(self, request_id: int) -> "dict[str, Any]":
        """ONE skew-corrected timeline for one request, stitched from
        every host's span fragments.

        Fetches ``trace(request_id)`` from all hosts, shifts each
        fragment by its host's estimated clock offset, deduplicates by
        span id (in-process hosts sharing a ring report the same
        spans), tags every span with the host it came from, and sorts.
        The ``phases`` key is :func:`stitch_phase_breakdown` over the
        result (None for a non-disaggregated request)."""
        _M_SCRAPES.inc(endpoint="trace")
        rid = int(request_id)
        offsets = self.clock_offsets()
        spans: "list[dict]" = []
        seen_span_ids: set = set()
        fragments: "dict[str, dict]" = {}
        for host_id, handle in self.hosts().items():
            try:
                out = handle.trace(rid)
            except Exception as e:
                _M_HOST_UP.set(0, host=host_id)
                fragments[host_id] = {"error": repr(e)}
                continue
            _M_HOST_UP.set(1, host=host_id)
            off = offsets.get(host_id, 0.0)
            host_spans = out.get("spans") or []
            fragments[host_id] = {
                "spans": len(host_spans),
                "clock_offset_us": off,
                "tier": self.tier_of(host_id),
            }
            for ev in host_spans:
                sid = (ev.get("args") or {}).get("span_id")
                if sid is not None:
                    if sid in seen_span_ids:
                        continue
                    seen_span_ids.add(sid)
                ev = dict(ev)
                ev["ts"] = float(ev.get("ts", 0.0)) - off
                ev["host"] = host_id
                spans.append(ev)
        spans.sort(key=lambda e: e["ts"])
        _M_STITCHED.inc()
        return {
            "request_id": rid,
            "spans": spans,
            "hosts": fragments,
            "phases": stitch_phase_breakdown(spans),
        }

    def export_fleet_trace(self, path: Any, request_id: int) -> int:
        """Write one stitched trace as Chrome ``trace_event`` JSON —
        the multi-host counterpart of ``tracing.export_chrome_trace``
        (loads in ui.perfetto.dev; one row per host via ``pid``).
        Returns the span count."""
        stitched = self.fleet_trace(request_id)
        events = []
        host_row = {h: i + 1
                    for i, h in enumerate(sorted(stitched["hosts"]))}
        for ev in stitched["spans"]:
            ev = dict(ev)
            # one timeline row per HOST, not per origin pid: the whole
            # point of stitching is reading the crossing at a glance
            ev["pid"] = host_row.get(ev.get("host"), 0)
            events.append(ev)
        with open(path, "w") as f:
            json.dump({"traceEvents": events, "displayTimeUnit": "ms"},
                      f, separators=(",", ":"), default=repr)
        return len(events)

    # -- fleet aggregation ----------------------------------------------------
    def fleet_metrics(self) -> str:
        """Prometheus text for the fleet: polls every host's
        ``capacity()`` into the ``sparkdl_fleet_*`` gauges, then
        renders this process's registry (the fleet families ride next
        to whatever else the aggregator process observes)."""
        _M_SCRAPES.inc(endpoint="metrics")
        for host_id, handle in self.hosts().items():
            try:
                handle.capacity()
            except Exception:
                _M_HOST_UP.set(0, host=host_id)
                continue
            _M_HOST_UP.set(1, host=host_id)
        return registry().to_prometheus()

    def fleet_slo(self) -> "dict[str, Any]":
        """Every host's SLO section plus this process's registered
        trackers — the ``/fleet/slo.json`` payload. Per-host sections
        come from ``snapshot()["slo"]`` where engines publish them;
        hosts without one report null rather than erroring the poll."""
        from sparkdl_tpu.observability import slo as slo_mod

        _M_SCRAPES.inc(endpoint="slo")
        hosts: "dict[str, Any]" = {}
        for host_id, handle in self.hosts().items():
            try:
                snap = handle.snapshot() or {}
            except Exception as e:
                _M_HOST_UP.set(0, host=host_id)
                hosts[host_id] = {"error": repr(e)}
                continue
            _M_HOST_UP.set(1, host=host_id)
            hosts[host_id] = {"slo": snap.get("slo"),
                              "tier": self.tier_of(host_id)}
        return {"slos": slo_mod.slo_report(), "hosts": hosts}

    def fleet_healthz(self) -> "dict[str, Any]":
        """Worst-of aggregation over every host's ``health()``:
        unhealthy if ANY host is unhealthy or unreachable, degraded if
        any is degraded, else ok — the strict grain a fleet-level pager
        wants (per-host state included for the triage that follows)."""
        _M_SCRAPES.inc(endpoint="healthz")
        rank = {"ok": 0, "degraded": 1, "unhealthy": 2}
        worst = "ok"
        hosts: "dict[str, Any]" = {}
        for host_id, handle in self.hosts().items():
            try:
                h = handle.health() or {}
            except Exception as e:
                h = {"status": "unhealthy", "error": repr(e)}
            _M_HOST_UP.set(
                0 if h.get("status") == "unhealthy" else 1,
                host=host_id)
            hosts[host_id] = h
            status = str(h.get("status", "unhealthy"))
            if rank.get(status, 2) > rank[worst]:
                worst = status if status in rank else "unhealthy"
        return {"status": worst, "hosts": hosts}


class _FleetHandler(BaseHTTPRequestHandler):
    scraper: FleetScraper  # set on the per-instance subclass

    def do_GET(self):  # noqa: N802 - BaseHTTPRequestHandler API
        path = self.path.split("?", 1)[0]
        status = 200
        try:
            if path == "/fleet/metrics":
                body = self.scraper.fleet_metrics().encode()
                ctype = "text/plain; version=0.0.4; charset=utf-8"
            elif path == "/fleet/slo.json":
                body = json.dumps(self.scraper.fleet_slo(),
                                  default=repr).encode()
                ctype = "application/json"
            elif path == "/fleet/healthz":
                report = self.scraper.fleet_healthz()
                status = 503 if report["status"] == "unhealthy" else 200
                body = json.dumps(report, default=repr).encode()
                ctype = "application/json"
            elif path.startswith("/fleet/trace/"):
                try:
                    rid = int(path.rsplit("/", 1)[1])
                except ValueError:
                    self.send_error(
                        400, "request id must be an integer")
                    return
                body = json.dumps(self.scraper.fleet_trace(rid),
                                  default=repr).encode()
                ctype = "application/json"
            else:
                self.send_error(404)
                return
        except Exception:
            _log.exception("fleet: %s handler failed", path)
            self.send_error(500)
            return
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt, *args):  # scrapes must not spam stdout
        _log.debug("fleet scrape: " + fmt, *args)


class FleetServer:
    """Serve one :class:`FleetScraper` over HTTP (daemon threads, same
    stdlib machinery as :class:`~sparkdl_tpu.observability.exporters.
    MetricsServer`): ``/fleet/metrics``, ``/fleet/slo.json``,
    ``/fleet/healthz``, ``/fleet/trace/<request_id>``."""

    def __init__(self, scraper: FleetScraper, *, port: int = 0,
                 host: str = ""):
        self.scraper = scraper
        handler = type("_BoundFleetHandler", (_FleetHandler,),
                       {"scraper": scraper})
        self._httpd = ThreadingHTTPServer((host, port), handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.2},
            name="sparkdl-fleet-http", daemon=True)
        self._thread.start()

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=2)

    def __enter__(self) -> "FleetServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
