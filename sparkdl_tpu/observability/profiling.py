"""Profiler hookup: per-host trace capture and trace server.

SURVEY.md §5 "Tracing / profiling": the reference has nothing in-repo; the
TPU equivalent is ``jax.profiler`` — XPlane/Perfetto traces showing XLA op
timing, infeed gaps and ICI collective overlap. Two entry points:

* :func:`trace` — capture a trace of a code block to a logdir (viewable in
  TensorBoard's profile plugin / Perfetto);
* :func:`start_trace_server` — long-lived per-host server so an operator
  can attach and sample a live job (the TPURunner worker starts one when
  ``SPARKDL_TPU_PROFILER_PORT`` is set).
"""

from __future__ import annotations

import contextlib
import os
from typing import Iterator

import jax


@contextlib.contextmanager
def trace(logdir: str | os.PathLike,
          create_perfetto_trace: bool = False) -> Iterator[None]:
    """Capture a ``jax.profiler`` trace of the enclosed block into ``logdir``.

    Remember to ``jax.block_until_ready`` the last output inside the block,
    otherwise async dispatch leaks device work past the capture window.
    """
    jax.profiler.start_trace(
        os.fspath(logdir), create_perfetto_trace=create_perfetto_trace
    )
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def start_trace_server(port: int = 9999):
    """Start the live profiling server on this host (one per process)."""
    return jax.profiler.start_server(port)


def annotate(name: str):
    """Named region that shows up on the trace timeline (host + device).

    Use around logical phases of a step (decode / infeed / apply) so the
    Perfetto view maps back to framework stages.
    """
    return jax.profiler.TraceAnnotation(name)
