"""Profiler hookup: device traces (jax.profiler) + host stack sampling.

SURVEY.md §5 "Tracing / profiling": the reference has nothing in-repo; the
TPU equivalent is ``jax.profiler`` — XPlane/Perfetto traces showing XLA op
timing, infeed gaps and ICI collective overlap. Device-side entry points:

* :func:`trace` — capture a trace of a code block to a logdir (viewable in
  TensorBoard's profile plugin / Perfetto);
* :func:`start_trace_server` — long-lived per-host server so an operator
  can attach and sample a live job (the TPURunner worker starts one when
  ``SPARKDL_TPU_PROFILER_PORT`` is set).

Host-side (ISSUE 9): the device trace shows what XLA did, not what the
*host* threads were doing while the chip starved — :func:`profile_block`
samples every Python thread's stack at a fixed cadence
(``sys._current_frames``, no instrumentation, a few µs per sample) and
writes a **collapsed-stack** file (``stack;frames;leaf count`` lines, the
format flamegraph.pl / speedscope / inferno eat directly). Benches wire
it behind ``SPARKDL_TPU_PROFILE=1`` via :func:`maybe_profile`, so "why is
the feed thread blocked" is one env var away on any bench run.
"""

from __future__ import annotations

import collections
import contextlib
import os
import sys
import threading
import time
from typing import Iterator

import jax

#: Truthy -> benches run under profile_block (see maybe_profile).
PROFILE_ENV = "SPARKDL_TPU_PROFILE"
#: Where maybe_profile writes its .folded files (default: cwd).
PROFILE_DIR_ENV = "SPARKDL_TPU_PROFILE_DIR"
#: Sampling cadence override, Hz (default 99 — deliberately not a round
#: 100 so the sampler cannot alias against 10ms-periodic work).
PROFILE_HZ_ENV = "SPARKDL_TPU_PROFILE_HZ"

#: Frames kept per stack (deeper tails are truncated at the root end).
_MAX_DEPTH = 128


@contextlib.contextmanager
def trace(logdir: str | os.PathLike,
          create_perfetto_trace: bool = False) -> Iterator[None]:
    """Capture a ``jax.profiler`` trace of the enclosed block into ``logdir``.

    Remember to ``jax.block_until_ready`` the last output inside the block,
    otherwise async dispatch leaks device work past the capture window.
    """
    jax.profiler.start_trace(
        os.fspath(logdir), create_perfetto_trace=create_perfetto_trace
    )
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def start_trace_server(port: int = 9999):
    """Start the live profiling server on this host (one per process)."""
    return jax.profiler.start_server(port)


def annotate(name: str):
    """Named region that shows up on the trace timeline (host + device).

    Use around logical phases of a step (decode / infeed / apply) so the
    Perfetto view maps back to framework stages.
    """
    return jax.profiler.TraceAnnotation(name)


class StackProfile:
    """Wall-clock sampler of every Python thread's stack.

    A daemon thread wakes every ``interval_s`` and snapshots
    ``sys._current_frames()`` — sampling, not tracing: zero cost between
    samples, a few µs per live thread per sample, and the result is a
    statistical flame graph of where host threads actually sit (queue
    waits, decode loops, GIL-held numpy stacking, ...). The sampler
    excludes itself.
    """

    def __init__(self, interval_s: float = 0.0101):
        if interval_s <= 0:
            raise ValueError(f"interval_s must be > 0, got {interval_s}")
        self.interval_s = interval_s
        #: collapsed stack (root-first, ';'-joined) -> sample count
        self.samples: "collections.Counter[str]" = collections.Counter()
        self.n_samples = 0
        self._stop = threading.Event()
        self._thread: "threading.Thread | None" = None

    def start(self) -> "StackProfile":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="sparkdl-stack-sampler", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)

    def _loop(self) -> None:
        me = threading.get_ident()
        while not self._stop.wait(self.interval_s):
            self.sample_once(_skip_ident=me)

    def sample_once(self, _skip_ident: "int | None" = None) -> None:
        """Take one sample of every live thread (public for tests)."""
        names = {t.ident: t.name for t in threading.enumerate()}
        for ident, frame in sys._current_frames().items():
            if ident == _skip_ident:
                continue
            stack = []
            f = frame
            while f is not None and len(stack) < _MAX_DEPTH:
                co = f.f_code
                stack.append(
                    f"{os.path.basename(co.co_filename)}:{co.co_name}"
                )
                f = f.f_back
            stack.append(names.get(ident, f"thread-{ident}"))
            self.samples[";".join(reversed(stack))] += 1
        self.n_samples += 1

    def write_collapsed(self, path: "str | os.PathLike") -> int:
        """Write the ``stack count`` lines flamegraph.pl / speedscope /
        inferno consume. Returns the number of distinct stacks."""
        with open(path, "w") as f:
            for stack, count in sorted(self.samples.items()):
                f.write(f"{stack} {count}\n")
        return len(self.samples)


@contextlib.contextmanager
def profile_block(path: "str | os.PathLike | None" = None, *,
                  interval_s: float = 0.0101) -> Iterator[StackProfile]:
    """Sample thread stacks for the duration of the block; write the
    collapsed-stack file to ``path`` on exit (skip the write with
    ``path=None`` and read ``.samples`` directly)."""
    prof = StackProfile(interval_s=interval_s).start()
    try:
        yield prof
    finally:
        prof.stop()
        if path is not None:
            prof.write_collapsed(path)


def maybe_profile(name: str):
    """The bench hook: a no-op context unless ``SPARKDL_TPU_PROFILE`` is
    truthy, in which case the block runs under :func:`profile_block`
    writing ``sparkdl-profile-<name>-<pid>.folded`` into
    ``SPARKDL_TPU_PROFILE_DIR`` (default cwd). The path is announced on
    stderr — bench stdout must stay one JSON line."""
    if os.environ.get(PROFILE_ENV, "") in ("", "0"):
        return contextlib.nullcontext(None)
    directory = os.environ.get(PROFILE_DIR_ENV) or "."
    path = os.path.join(
        directory, f"sparkdl-profile-{name}-{os.getpid()}.folded"
    )
    hz = float(os.environ.get(PROFILE_HZ_ENV, "99"))
    if hz <= 0:
        raise ValueError(
            f"{PROFILE_HZ_ENV} must be > 0, got {hz} (unset "
            f"{PROFILE_ENV} to disable profiling instead)"
        )

    @contextlib.contextmanager
    def _ctx():
        t0 = time.perf_counter()
        with profile_block(path, interval_s=1.0 / hz) as prof:
            yield prof
        print(
            f"[profile] {prof.n_samples} samples over "
            f"{time.perf_counter() - t0:.1f}s -> {path} "
            "(flamegraph.pl / speedscope-compatible collapsed stacks)",
            file=sys.stderr,
        )

    return _ctx()
