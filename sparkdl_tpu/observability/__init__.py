"""Metrics, profiling and health — the observability the reference lacks.

The reference's story is "Spark executor logs + whatever TF timeline offers"
(SURVEY.md §5 "Tracing / profiling": absent as a subsystem; "Metrics": thin
stdout piping). The TPU build makes this first-class:

* :mod:`sparkdl_tpu.observability.metrics` — step-time / examples-per-sec
  per chip / MFU / infeed-starvation meters, with compiled-FLOPs lookup from
  XLA cost analysis;
* :mod:`sparkdl_tpu.observability.profiling` — ``jax.profiler`` trace
  capture (Perfetto/XPlane) as a context manager plus a per-host trace
  server;
* :mod:`sparkdl_tpu.observability.health` — device/collective health probe
  run before ``jax.distributed`` training starts (SURVEY.md §5 "Failure
  detection": TPU slice health check before initialize).
"""

from sparkdl_tpu.observability.health import HealthReport, check_health
from sparkdl_tpu.observability.metrics import (
    StepMeter,
    aggregate_across_hosts,
    compiled_flops,
    device_peak_flops,
    percentile,
)
from sparkdl_tpu.observability.profiling import start_trace_server, trace

__all__ = [
    "HealthReport",
    "StepMeter",
    "aggregate_across_hosts",
    "check_health",
    "compiled_flops",
    "device_peak_flops",
    "percentile",
    "start_trace_server",
    "trace",
]
