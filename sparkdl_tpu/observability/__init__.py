"""Metrics, tracing, profiling and health — the observability the
reference lacks.

The reference's story is "Spark executor logs + whatever TF timeline offers"
(SURVEY.md §5 "Tracing / profiling": absent as a subsystem; "Metrics": thin
stdout piping). The TPU build makes this first-class, around one spine:

* :mod:`sparkdl_tpu.observability.registry` — the process-wide
  :func:`registry` of counters / gauges / bucketed histograms every layer
  (serving, prefetch, batching, training, checkpointing) reports into;
  ``registry().snapshot()`` is the one-call JSON view, and
  :func:`snapshot_across_hosts` rolls it up over a multi-host job;
* :mod:`sparkdl_tpu.observability.exporters` — Prometheus ``/metrics``
  endpoint (opt-in via ``SPARKDL_TPU_METRICS_PORT``) and a periodic
  logline emitter;
* :mod:`sparkdl_tpu.observability.tracing` — ``span("decode", ...)``
  request/step tracing with contextvar propagation and Chrome
  ``trace_event`` JSON export (Perfetto-loadable, next to
  ``jax.profiler`` captures); span wall times feed the
  ``sparkdl_stage_seconds`` histogram so per-stage p50/p95/p99 ride the
  same registry;
* :mod:`sparkdl_tpu.observability.metrics` — step-time / examples-per-sec
  per chip / MFU / infeed-starvation meters, with compiled-FLOPs lookup from
  XLA cost analysis;
* :mod:`sparkdl_tpu.observability.profiling` — ``jax.profiler`` trace
  capture (Perfetto/XPlane) as a context manager plus a per-host trace
  server, and :func:`profile_block` host stack sampling (collapsed-stack
  output, ``SPARKDL_TPU_PROFILE=1`` in the benches);
* :mod:`sparkdl_tpu.observability.health` — device/collective health probe
  run before ``jax.distributed`` training starts (SURVEY.md §5 "Failure
  detection": TPU slice health check before initialize);
* :mod:`sparkdl_tpu.observability.flight` — the flight recorder: bounded
  ring of reliability events (faults, retries, quarantines, autotune
  decisions, span completions) with reliability-triggered postmortem
  bundles, plus the ``/healthz`` aggregation;
* :mod:`sparkdl_tpu.observability.slo` — declared latency/availability
  objectives with rolling error-budget burn, surfaced in engine
  snapshots, ``sparkdl_slo_*`` gauges and ``/slo.json``.
"""

from sparkdl_tpu.observability.exporters import (
    MetricsServer,
    PeriodicLogEmitter,
    maybe_start_metrics_server,
)
from sparkdl_tpu.observability.flight import (
    FlightRecorder,
    flight_recorder,
    healthz_report,
    record_event,
    trigger_dump,
)
from sparkdl_tpu.observability.health import HealthReport, check_health
from sparkdl_tpu.observability.metrics import (
    StepMeter,
    aggregate_across_hosts,
    compiled_flops,
    device_peak_flops,
    percentile,
)
from sparkdl_tpu.observability.profiling import (
    StackProfile,
    maybe_profile,
    profile_block,
    start_trace_server,
    trace,
)
from sparkdl_tpu.observability.registry import (
    MetricsRegistry,
    registry,
    snapshot_across_hosts,
)
from sparkdl_tpu.observability.slo import SLO, SLOTracker, slo_report
from sparkdl_tpu.observability.tracing import (
    attach,
    current_context,
    disable_tracing,
    enable_tracing,
    export_chrome_trace,
    record_span,
    span,
    spans_for_trace,
    tracing_enabled,
)

__all__ = [
    "FlightRecorder",
    "HealthReport",
    "MetricsRegistry",
    "MetricsServer",
    "PeriodicLogEmitter",
    "SLO",
    "SLOTracker",
    "StackProfile",
    "StepMeter",
    "aggregate_across_hosts",
    "attach",
    "check_health",
    "compiled_flops",
    "current_context",
    "device_peak_flops",
    "disable_tracing",
    "enable_tracing",
    "export_chrome_trace",
    "flight_recorder",
    "healthz_report",
    "maybe_profile",
    "maybe_start_metrics_server",
    "percentile",
    "profile_block",
    "record_event",
    "record_span",
    "registry",
    "slo_report",
    "snapshot_across_hosts",
    "span",
    "spans_for_trace",
    "start_trace_server",
    "trace",
    "tracing_enabled",
    "trigger_dump",
]
