"""Span-based request/step tracing with Chrome ``trace_event`` export.

The timeline view the TensorFlow system paper models (PAPERS.md): every
stage of the online path — queue wait, micro-batch assembly, device step —
and the batch path — ingest, prefetch, run_batch — is a ``span`` whose
wall time lands both in a Chrome/Perfetto-loadable JSON trace (open it in
ui.perfetto.dev next to a ``jax.profiler`` capture) and in the
``sparkdl_stage_seconds`` histogram of the metrics registry, so per-stage
p50/p95/p99 come for free wherever tracing is on.

Disabled by default: ``span()`` then returns a shared no-op context
manager (< 1µs per use — guarded by a test) so the serving hot loop pays
nothing. Enable with ``SPARKDL_TPU_TRACE=1`` in the environment or
:func:`enable_tracing` in code.

Cross-thread propagation: parentage rides a :mod:`contextvars` var inside
a thread; across threads (a submitting caller → the MicroBatcher worker)
the producer captures :func:`current_context` and the consumer re-roots
with :func:`attach` — the pattern ``serving/queue.py`` uses so a request's
queue-wait and device-step spans hang off the submitter's trace.

Per-request traces (ISSUE 9): every serving request is allocated a
FLEET-unique id at ``RequestQueue.submit`` (:func:`next_request_id` —
an int, the ONLY per-request cost with tracing off) that doubles as its
trace id. :func:`request_context` roots the request's trace; stage spans
(queue wait, prefill, the terminal ``serving.request``) parent on it,
while batch-level spans — one device dispatch serving many riders — run
in their OWN trace carrying a ``links=[request ids...]`` attribute that
fans them into every rider's trace. :func:`spans_for_trace` resolves one
request id to its full span set (direct spans + linked batch traces);
``ServingEngine.trace(request_id)`` is the operator surface over it.

Fleet uniqueness (ISSUE 17): ids are host-qualified — the high bits are
a stable per-process host hash (:func:`host_hash`, derived from the
fabric ``host_id``: ``SPARKDL_TPU_HOST_ID`` or ``hostname:pid``), the
low 32 bits a local counter — so two hosts can NEVER mint colliding
trace ids and a :class:`SpanContext` can cross processes
(:func:`context_to_wire` / :func:`context_from_wire`, shipped in the
fabric submit payload and ``KVHandoff.to_wire``). The receiving host
``attach()``\\ es the deserialized context so prefill-tier, handoff, and
decode-tier spans parent into ONE stitched trace
(``observability/fleet.py`` is the cross-host aggregation surface).
"""

from __future__ import annotations

import collections
import contextvars
import itertools
import json
import os
import threading
import time
from dataclasses import dataclass
from typing import Any

__all__ = [
    "SpanContext",
    "attach",
    "clear_trace",
    "context_from_wire",
    "context_to_wire",
    "current_context",
    "disable_tracing",
    "enable_tracing",
    "export_chrome_trace",
    "host_hash",
    "host_of_id",
    "new_trace_context",
    "next_request_id",
    "observe_stage",
    "record_span",
    "request_context",
    "set_trace_host",
    "span",
    "spans_for_trace",
    "trace_clock_us",
    "trace_events",
    "tracing_enabled",
]

#: Stage-duration histogram every finished span observes into.
STAGE_METRIC = "sparkdl_stage_seconds"

_stage_family = None
_stage_bound: "dict[str, Any]" = {}


def observe_stage(stage: str, seconds: float) -> None:
    """Record a stage duration in the ``sparkdl_stage_seconds`` histogram.

    The single owner of that metric's schema: every finished span feeds
    through here, and instrumentation that times a stage without a span
    (bench loops) calls it directly. Bound handles are cached per stage so
    the hot path pays one dict hit + a float add."""
    global _stage_family
    bound = _stage_bound.get(stage)
    if bound is None:
        if _stage_family is None:
            from sparkdl_tpu.observability.registry import registry

            _stage_family = registry().histogram(
                STAGE_METRIC, "per-stage span wall time", labels=("stage",)
            )
        # benign race: .labels() caches under the family lock, so two
        # threads resolving the same stage get the same bound object
        bound = _stage_bound[stage] = _stage_family.labels(stage=stage)
    bound.observe(seconds)

_enabled: bool = os.environ.get("SPARKDL_TPU_TRACE", "") not in ("", "0")
_ids = itertools.count(1)
_ids_lock = threading.Lock()
#: bounded ring of finished-span events (dicts in trace_event shape)
_events: "collections.deque[dict]" = collections.deque(maxlen=100_000)
#: seconds origin for trace timestamps; one epoch per process so spans
#: from every thread land on a common clock
_EPOCH = time.monotonic()

_now = time.monotonic

#: bits reserved for the per-host local counter in every minted id
HOST_ID_SHIFT = 32


def _stable_host_hash(host_id: str) -> int:
    """Deterministic 31-bit hash of a host identity string (NOT
    ``hash()``, which is salted per process — the same ``host_id`` must
    map to the same id prefix across restarts so traces and logs remain
    joinable)."""
    import zlib

    return (zlib.crc32(host_id.encode()) & 0x7FFFFFFF) or 1


def _default_host_identity() -> str:
    env = os.environ.get("SPARKDL_TPU_HOST_ID")
    if env:
        return env
    import socket

    return f"{socket.gethostname()}:{os.getpid()}"


_host_hash: int = _stable_host_hash(_default_host_identity())
#: precomputed high-bits base so the minting hot path is one OR
_id_base: int = _host_hash << HOST_ID_SHIFT


def host_hash() -> int:
    """This process's 31-bit stable host hash — the high bits of every
    id :func:`next_request_id` mints (fleet uniqueness, ISSUE 17)."""
    return _host_hash


def host_of_id(any_id: int) -> int:
    """The host hash folded into a request/span id (0 for pre-17 ids)."""
    return int(any_id) >> HOST_ID_SHIFT


def set_trace_host(host_id: str) -> int:
    """Re-key this process's id space to ``host_id`` (returns the new
    :func:`host_hash`). Operators pin identity via ``SPARKDL_TPU_HOST_ID``
    before import; this is the in-code override (tests simulating a
    foreign host, a fabric process adopting its assigned id late).
    Already-minted ids keep their old prefix — ids only ever need to be
    unique, not re-derivable."""
    global _host_hash, _id_base
    _host_hash = _stable_host_hash(host_id)
    _id_base = _host_hash << HOST_ID_SHIFT
    return _host_hash


def trace_clock_us() -> float:
    """This process's trace clock: µs since its span-timestamp epoch —
    the same timebase ``ts`` in :func:`trace_events` uses. A fleet
    scraper reads it over the trace RPC and estimates per-host clock
    offset from the RPC round-trip midpoint (``fleet.FleetScraper``);
    monotonic clocks never cross processes raw."""
    return (time.monotonic() - _EPOCH) * 1e6


@dataclass(frozen=True)
class SpanContext:
    """Identity of a live or finished span, safe to ship across threads."""

    trace_id: int
    span_id: int


_current: "contextvars.ContextVar[SpanContext | None]" = \
    contextvars.ContextVar("sparkdl_tpu_span", default=None)


def tracing_enabled() -> bool:
    return _enabled


def enable_tracing() -> None:
    global _enabled
    _enabled = True


def disable_tracing() -> None:
    global _enabled
    _enabled = False


def current_context() -> "SpanContext | None":
    """The innermost active span of THIS thread (None outside any span, or
    with tracing off). Capture at a boundary, re-attach with :func:`attach`."""
    if not _enabled:
        return None
    return _current.get()


def _next_id() -> int:
    with _ids_lock:
        return _id_base | next(_ids)


def next_request_id() -> int:
    """Fleet-unique id for one serving request; doubles as its trace
    id. High bits are this host's stable hash (:func:`host_hash`), low
    bits a local counter — two hosts cannot collide, so a
    ``DecodeWorker`` adopting a foreign id (ISSUE 16/17) can never be
    handed an id this process will later mint. Allocated unconditionally
    at submit — with tracing disabled this int is the ONLY per-request
    tracing cost (guarded by run-tests.sh)."""
    with _ids_lock:
        return _id_base | next(_ids)


def request_context(request_id: int) -> "SpanContext | None":
    """Root span context of one request's trace (``trace_id`` IS the
    request id). None with tracing off — zero allocation there."""
    if not _enabled:
        return None
    return SpanContext(request_id, request_id)


def new_trace_context() -> "SpanContext | None":
    """Root context for a fresh trace — what batch-level work (a device
    dispatch serving many riders) runs under, with a ``links=[...]``
    attribute on its spans fanning it into each rider's trace. None with
    tracing off."""
    if not _enabled:
        return None
    tid = _next_id()
    return SpanContext(tid, tid)


class _Attach:
    __slots__ = ("_ctx", "_token")

    def __init__(self, ctx: "SpanContext | None"):
        self._ctx = ctx
        self._token = None

    def __enter__(self):
        self._token = _current.set(self._ctx)
        return self._ctx

    def __exit__(self, *exc):
        _current.reset(self._token)
        return False


def attach(ctx: "SpanContext | None") -> _Attach:
    """Context manager making ``ctx`` the ambient parent in this thread —
    the receiving half of cross-thread propagation."""
    return _Attach(ctx)


def context_to_wire(ctx: "SpanContext | None") -> "dict | None":
    """Serialize a :class:`SpanContext` for a cross-process hop (the
    fabric submit body, ``KVHandoff.to_wire``). None stays None — a
    tracing-off sender ships nothing."""
    if ctx is None:
        return None
    return {"trace_id": int(ctx.trace_id), "span_id": int(ctx.span_id)}


def context_from_wire(d: "dict | None") -> "SpanContext | None":
    """Rebuild a shipped :class:`SpanContext` on the receiving host.
    None with tracing off (the receiver pays zero, matching
    :func:`request_context`'s convention) or for an absent/garbled
    payload — propagation is best-effort, never a request failure."""
    if not _enabled or not isinstance(d, dict):
        return None
    try:
        return SpanContext(int(d["trace_id"]), int(d["span_id"]))
    except (KeyError, TypeError, ValueError):
        return None


class _NoopSpan:
    """Shared do-nothing span (tracing disabled fast path)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    #: parity with _Span so instrumentation never branches on the type
    context: "SpanContext | None" = None

    def set_attr(self, **attrs: Any) -> None:
        pass


_NOOP = _NoopSpan()


class _Span:
    __slots__ = ("name", "attrs", "context", "_parent", "_token", "_start")

    def __init__(self, name: str, parent: "SpanContext | None",
                 attrs: "dict[str, Any]"):
        self.name = name
        self.attrs = attrs
        self._parent = parent
        trace_id = parent.trace_id if parent is not None else _next_id()
        self.context = SpanContext(trace_id, _next_id())

    def set_attr(self, **attrs: Any) -> None:
        self.attrs.update(attrs)

    def __enter__(self) -> "_Span":
        self._token = _current.set(self.context)
        self._start = _now()
        return self

    def __exit__(self, exc_type, *exc) -> bool:
        end = _now()
        _current.reset(self._token)
        if exc_type is not None:
            self.attrs["error"] = exc_type.__name__
        _finish(self.name, self._start, end, self.context,
                self._parent, self.attrs)
        return False


def span(name: str, parent: "SpanContext | None" = None,
         **attrs: Any):
    """Open a span: ``with span("serving.device_step", rows=n): ...``.

    Parent defaults to the thread's ambient span (contextvar); pass
    ``parent=`` to re-root explicitly (e.g. a request's captured submit
    context). With tracing disabled this returns a shared no-op and costs
    well under a microsecond.
    """
    if not _enabled:
        return _NOOP
    if parent is None:
        parent = _current.get()
    return _Span(name, parent, attrs)


def record_span(name: str, start_s: float, end_s: float,
                parent: "SpanContext | None" = None,
                **attrs: Any) -> "SpanContext | None":
    """Record an already-elapsed interval as a finished span.

    For stages whose start predates the instrumentation point — queue
    wait is measured at ``take()`` from the request's enqueue stamp.
    ``start_s``/``end_s`` are ``time.monotonic()`` seconds (the clock
    :class:`Request` stamps with). No-op with tracing disabled.
    """
    if not _enabled:
        return None
    trace_id = parent.trace_id if parent is not None else _next_id()
    ctx = SpanContext(trace_id, _next_id())
    _finish(name, start_s, end_s, ctx, parent, attrs)
    return ctx


def _finish(name: str, start_s: float, end_s: float, ctx: SpanContext,
            parent: "SpanContext | None", attrs: "dict[str, Any]") -> None:
    dur = max(end_s - start_s, 0.0)
    args = {"trace_id": ctx.trace_id, "span_id": ctx.span_id}
    if parent is not None:
        args["parent_id"] = parent.span_id
    for k, v in attrs.items():
        if isinstance(v, (int, float, bool, str)):
            args[k] = v
        elif isinstance(v, (list, tuple)) and all(
                isinstance(i, (int, float, bool, str)) for i in v):
            # link lists (rider request ids on batch spans) stay
            # structured: spans_for_trace matches against them
            args[k] = list(v)
        else:
            args[k] = repr(v)
    _events.append({
        "name": name,
        "ph": "X",
        "ts": (start_s - _EPOCH) * 1e6,
        "dur": dur * 1e6,
        "pid": os.getpid(),
        "tid": threading.get_ident() & 0x7FFFFFFF,
        "args": args,
    })
    observe_stage(name, dur)
    # every span completion is also a flight-recorder event (ISSUE 9) —
    # in the recorder's DEDICATED span ring, so high-rate span traffic
    # can never evict the sparse reliability events postmortems need
    from sparkdl_tpu.observability import flight

    flight.flight_recorder().record_span_event(
        name, trace_id=ctx.trace_id, span_id=ctx.span_id,
        dur_ms=round(dur * 1e3, 3),
    )


def trace_events() -> "list[dict]":
    """The finished-span ring as plain dicts (test/inspection hook).

    Copied via the shared hot-append-safe snapshot (a postmortem dump
    taken under load must get the ring, not a RuntimeError from a
    concurrent span finish)."""
    from sparkdl_tpu.observability.flight import safe_ring_snapshot

    return safe_ring_snapshot(_events)


def spans_for_trace(trace_id: int, *, follow_links: bool = True,
                    events: "list[dict] | None" = None) -> "list[dict]":
    """Every finished span of one trace, timestamp-ordered.

    A request's trace id is its request id (:func:`next_request_id`), so
    ``spans_for_trace(fut.request_id)`` answers "what happened to THIS
    request". Matching is two-level: spans whose ``trace_id`` equals (or
    whose ``links`` list contains) the id are direct members; with
    ``follow_links`` (default) the batch traces those linked spans
    belong to are pulled in whole — the device dispatch, replica
    execution and fetch spans a rider shared with its batch-mates.
    ``events`` lets a caller resolving MANY traces (a postmortem dump)
    snapshot the ring once instead of per call.
    """
    evs = events if events is not None else trace_events()
    picked: "list[dict]" = []
    span_ids: "set" = set()
    related: "set" = set()
    for ev in evs:
        args = ev.get("args", {})
        links = args.get("links")
        if args.get("trace_id") == trace_id or (
                isinstance(links, list) and trace_id in links):
            picked.append(ev)
            span_ids.add(args.get("span_id"))
            related.add(args.get("trace_id"))
    related.discard(trace_id)
    if follow_links and related:
        for ev in evs:
            args = ev.get("args", {})
            if (args.get("trace_id") in related
                    and args.get("span_id") not in span_ids):
                picked.append(ev)
                span_ids.add(args.get("span_id"))
    picked.sort(key=lambda e: e["ts"])
    return picked


def clear_trace() -> None:
    _events.clear()


def export_chrome_trace(path: "str | os.PathLike",
                        trace_id: "int | None" = None) -> int:
    """Write the collected spans as Chrome ``trace_event`` JSON.

    The file loads in ``chrome://tracing`` and https://ui.perfetto.dev —
    same UIs that read ``jax.profiler`` captures, so serving spans and
    XLA device traces can sit side by side. ``trace_id`` (e.g. a
    request id) exports only that trace (linked batch spans included).
    Returns the event count.
    """
    events = (trace_events() if trace_id is None
              else spans_for_trace(trace_id))
    with open(path, "w") as f:
        json.dump(
            {"traceEvents": events, "displayTimeUnit": "ms"}, f,
            separators=(",", ":"),
        )
    return len(events)
